"""NaiveBayes kernel tests: correctness vs a pure-numpy oracle, and
mesh-sharded == single-device (the distributed-equivalence property that
replaces trusting Spark's aggregate)."""

import numpy as np
import pytest

from predictionio_tpu.models import naive_bayes


def numpy_multinomial_nb(features, labels, num_classes, smoothing):
    n, f = features.shape
    log_prior = np.zeros(num_classes)
    log_theta = np.zeros((num_classes, f))
    for c in range(num_classes):
        rows = features[labels == c]
        # MLlib NaiveBayes prior: log(n_c + λ) - log(N + C·λ)
        log_prior[c] = np.log(len(rows) + smoothing) - np.log(
            n + smoothing * num_classes
        )
        sums = rows.sum(axis=0)
        log_theta[c] = np.log((sums + smoothing) / (sums.sum() + smoothing * f))
    return log_prior, log_theta


@pytest.fixture
def dataset():
    rng = np.random.default_rng(0)
    n, f, c = 200, 6, 3
    labels = rng.integers(0, c, size=n).astype(np.int32)
    centers = rng.uniform(1, 10, size=(c, f))
    features = rng.poisson(centers[labels]).astype(np.float32)
    return features, labels, c


def test_multinomial_matches_numpy_oracle(dataset):
    features, labels, c = dataset
    model = naive_bayes.train_multinomial(features, labels, c, smoothing=1.0)
    log_prior, log_theta = numpy_multinomial_nb(features, labels, c, 1.0)
    np.testing.assert_allclose(np.asarray(model.log_prior), log_prior, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(model.log_theta), log_theta, rtol=1e-4)


def test_multinomial_mesh_equals_single_device(dataset, mesh8):
    features, labels, c = dataset
    single = naive_bayes.train_multinomial(features, labels, c)
    sharded = naive_bayes.train_multinomial(features, labels, c, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(single.log_theta), np.asarray(sharded.log_theta), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(single.log_prior), np.asarray(sharded.log_prior), rtol=1e-5
    )


def test_multinomial_mesh_with_ragged_length(mesh8):
    """n not divisible by the data axis: padding must not change counts."""
    rng = np.random.default_rng(1)
    n = 37  # not a multiple of 8
    features = rng.poisson(3, size=(n, 4)).astype(np.float32)
    labels = rng.integers(0, 2, size=n).astype(np.int32)
    single = naive_bayes.train_multinomial(features, labels, 2)
    sharded = naive_bayes.train_multinomial(features, labels, 2, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(single.log_theta), np.asarray(sharded.log_theta), rtol=1e-5
    )


def test_multinomial_predictions_recover_structure(dataset):
    features, labels, c = dataset
    model = naive_bayes.train_multinomial(features, labels, c)
    preds = naive_bayes.predict_multinomial(model, features)
    assert (preds == labels).mean() > 0.8  # poisson clusters are separable


def test_categorical_counts_and_unseen():
    # feature 0: value==label exactly; feature 1: constant (uninformative)
    features = np.array([[0, 1], [1, 1], [0, 1], [1, 1]], dtype=np.int32)
    labels = np.array([0, 1, 0, 1], dtype=np.int32)
    model = naive_bayes.train_categorical(features, labels, num_classes=2, num_values=3)
    preds = naive_bayes.predict_categorical(model, features)
    np.testing.assert_array_equal(preds, labels)
    # unseen value (-1) falls back to default score, still predicts via prior
    p = naive_bayes.predict_categorical(model, np.array([[-1, -1]], dtype=np.int32))
    assert p.shape == (1,)


def test_categorical_mesh_equals_single(mesh8):
    rng = np.random.default_rng(2)
    features = rng.integers(0, 5, size=(50, 3)).astype(np.int32)
    labels = rng.integers(0, 4, size=50).astype(np.int32)
    single = naive_bayes.train_categorical(features, labels, 4, 5)
    sharded = naive_bayes.train_categorical(features, labels, 4, 5, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(single.log_likelihood), np.asarray(sharded.log_likelihood), rtol=1e-5
    )
