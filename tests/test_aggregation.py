"""Property aggregation tests: local fold + EventOp monoid.

Modeled on LEventAggregatorSpec / PEventAggregatorSpec over the shared
TestEvents fixture (reference: data/src/test/scala/.../storage/
{LEventAggregatorSpec,PEventAggregatorSpec,TestEvents}.scala). The key
extra property tested here: the EventOp monoid must agree with the
ordered local fold under any partitioning/permutation of the events —
that is what makes shard-parallel aggregation correct.
"""

import itertools
import random
from datetime import datetime, timedelta, timezone

from predictionio_tpu.core.aggregation import (
    EventOp,
    aggregate_properties,
    aggregate_properties_parallel,
    aggregate_properties_single,
)
from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event


T0 = datetime(2020, 1, 1, tzinfo=timezone.utc)


def ev(name, entity, minutes, props=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=entity,
        properties=DataMap(props or {}),
        event_time=T0 + timedelta(minutes=minutes),
    )


def test_set_merge_last_wins():
    pm = aggregate_properties_single(
        [
            ev("$set", "u1", 0, {"a": 1, "b": 2}),
            ev("$set", "u1", 10, {"b": 20, "c": 30}),
        ]
    )
    assert pm.fields == {"a": 1, "b": 20, "c": 30}
    assert pm.first_updated == T0
    assert pm.last_updated == T0 + timedelta(minutes=10)


def test_unset_removes_fields():
    pm = aggregate_properties_single(
        [
            ev("$set", "u1", 0, {"a": 1, "b": 2}),
            ev("$unset", "u1", 5, {"a": None}),
        ]
    )
    assert pm.fields == {"b": 2}


def test_delete_then_nothing():
    assert (
        aggregate_properties_single(
            [ev("$set", "u1", 0, {"a": 1}), ev("$delete", "u1", 5)]
        )
        is None
    )


def test_delete_then_set_again():
    pm = aggregate_properties_single(
        [
            ev("$set", "u1", 0, {"a": 1, "b": 2}),
            ev("$delete", "u1", 5),
            ev("$set", "u1", 10, {"c": 3}),
        ]
    )
    assert pm.fields == {"c": 3}


def test_non_special_events_ignored():
    pm = aggregate_properties_single(
        [
            ev("$set", "u1", 0, {"a": 1}),
            ev("rate", "u1", 5, {"rating": 5}),
        ]
    )
    assert pm.fields == {"a": 1}
    assert pm.last_updated == T0  # rate event does not touch updated times
    assert aggregate_properties_single([ev("rate", "u1", 5, {"r": 1})]) is None


def test_group_by_entity_and_filter_deleted():
    out = aggregate_properties(
        [
            ev("$set", "u1", 0, {"a": 1}),
            ev("$set", "u2", 0, {"b": 2}),
            ev("$delete", "u2", 1),
            ev("rate", "u3", 0, {"r": 1}),
        ]
    )
    assert set(out) == {"u1"}
    assert out["u1"].fields == {"a": 1}


EVENT_STREAM = [
    ev("$set", "u1", 0, {"a": 1, "b": 2, "c": 3}),
    ev("$unset", "u1", 4, {"b": None}),
    ev("$set", "u1", 7, {"b": 22, "d": 4}),
    ev("$delete", "u1", 9),
    ev("$set", "u1", 11, {"e": 5}),
    ev("$set", "u1", 13, {"a": 10}),
    ev("$unset", "u1", 15, {"e": None}),
    ev("rate", "u1", 16, {"ignored": 1}),
    ev("$set", "u2", 2, {"x": 1}),
    ev("$delete", "u3", 1),
]


def test_monoid_matches_local_fold_under_permutation():
    expected = aggregate_properties(EVENT_STREAM)
    rng = random.Random(0)
    for _ in range(25):
        shuffled = EVENT_STREAM[:]
        rng.shuffle(shuffled)
        # random partition into 3 shards
        shards = [[], [], []]
        for e in shuffled:
            shards[rng.randrange(3)].append(e)
        got = aggregate_properties_parallel(shards)
        assert set(got) == set(expected)
        for k in expected:
            assert got[k].fields == expected[k].fields, k
            assert got[k].first_updated == expected[k].first_updated
            assert got[k].last_updated == expected[k].last_updated


def test_monoid_associativity():
    ops = [EventOp.from_event(e) for e in EVENT_STREAM if e.entity_id == "u1"]
    # fold left vs fold right vs tree
    left = ops[0]
    for o in ops[1:]:
        left = left + o
    right = ops[-1]
    for o in reversed(ops[:-1]):
        right = o + right
    assert left.to_property_map().fields == right.to_property_map().fields
    for a, b, c in itertools.combinations(ops, 3):
        assert ((a + b) + c).to_property_map() == (a + (b + c)).to_property_map() or (
            ((a + b) + c).to_property_map().fields == (a + (b + c)).to_property_map().fields
        )


def test_unset_without_set_is_none():
    assert EventOp.from_event(ev("$unset", "u1", 0, {"a": 1})).to_property_map() is None
    assert EventOp.from_event(ev("$delete", "u1", 0)).to_property_map() is None
    assert EventOp().to_property_map() is None
