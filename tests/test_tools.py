"""Dashboard, Admin API, export/import, SelfCleaningDataSource tests
(reference specs: AdminAPISpec, the dashboard twirl listing, EventsToFile/
FileToEvents drivers, SelfCleaningDataSource behavior)."""

from __future__ import annotations

import importlib.util
import io
import json
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.data.self_cleaning import EventWindow, SelfCleaningDataSource
from predictionio_tpu.storage.base import App, EventFilter
from predictionio_tpu.tools.admin import AdminServer
from predictionio_tpu.tools.dashboard import Dashboard
from predictionio_tpu.tools.export_import import export_events, import_events


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.headers.get_content_type(), r.read().decode()


def _req(url, method, payload=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# Admin API
# ---------------------------------------------------------------------------

@pytest.fixture
def admin(storage):
    server = AdminServer(storage, ip="127.0.0.1", port=0)
    server.start()
    yield server, storage
    server.stop()


class TestAdminAPI:
    def test_health(self, admin):
        server, _ = admin
        _, payload = _req(f"http://127.0.0.1:{server.port}/", "GET")
        assert payload == {"status": "alive"}

    def test_app_lifecycle(self, admin):
        server, storage = admin
        base = f"http://127.0.0.1:{server.port}"

        status, created = _req(f"{base}/cmd/app", "POST", {"name": "AdminApp"})
        assert status == 201
        assert created["name"] == "AdminApp"
        assert created["accessKey"]

        _, listing = _req(f"{base}/cmd/app", "GET")
        assert [a["name"] for a in listing["apps"]] == ["AdminApp"]

        # duplicate -> 409
        with pytest.raises(urllib.error.HTTPError) as e:
            _req(f"{base}/cmd/app", "POST", {"name": "AdminApp"})
        assert e.value.code == 409

        # seed an event, then data-delete clears it
        app = storage.get_meta_data_apps().get_by_name("AdminApp")
        storage.get_events().insert(
            Event(event="buy", entity_type="user", entity_id="u1"), app.id
        )
        assert len(list(storage.get_events().find(app.id, filter=EventFilter()))) == 1
        status, _ = _req(f"{base}/cmd/app/AdminApp/data", "DELETE")
        assert status == 200
        assert list(storage.get_events().find(app.id, filter=EventFilter())) == []

        status, _ = _req(f"{base}/cmd/app/AdminApp", "DELETE")
        assert status == 200
        _, listing = _req(f"{base}/cmd/app", "GET")
        assert listing["apps"] == []

    def test_missing_app_404(self, admin):
        server, _ = admin
        with pytest.raises(urllib.error.HTTPError) as e:
            _req(f"http://127.0.0.1:{server.port}/cmd/app/nope", "DELETE")
        assert e.value.code == 404


# ---------------------------------------------------------------------------
# Dashboard
# ---------------------------------------------------------------------------

class TestDashboard:
    def test_lists_completed_evaluations(self, storage):
        # persist one completed evaluation through the real workflow
        from predictionio_tpu.controller import EngineParamsGenerator
        from predictionio_tpu.workflow.evaluation import run_evaluation
        from tests.cli_eval_support import CliEvaluation, CliParamsList

        outcome = run_evaluation(CliEvaluation(), CliParamsList(), storage=storage)

        dash = Dashboard(storage, ip="127.0.0.1", port=0)
        dash.start()
        try:
            base = f"http://127.0.0.1:{dash.port}"
            _, ctype, body = _get(f"{base}/")
            assert ctype == "text/html"
            assert outcome.instance_id in body

            _, ctype, txt = _get(
                f"{base}/engine_instances/{outcome.instance_id}/evaluator_results.txt"
            )
            assert ctype == "text/plain"
            assert txt == outcome.result.to_one_liner()

            _, _, js = _get(
                f"{base}/engine_instances/{outcome.instance_id}/evaluator_results.json"
            )
            assert json.loads(js)["bestIdx"] == outcome.result.best_idx

            _, ctype, html_body = _get(
                f"{base}/engine_instances/{outcome.instance_id}/evaluator_results.html"
            )
            assert "<table" in html_body

            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{base}/engine_instances/zzz/evaluator_results.txt")
            assert e.value.code == 404
        finally:
            dash.stop()

    def test_cors_headers(self, storage):
        """Parity: CorsSupport.scala:31-77 — allow-origin on every
        response, preflight OPTIONS with methods/headers/max-age."""
        dash = Dashboard(storage, ip="127.0.0.1", port=0)
        dash.start()
        try:
            base = f"http://127.0.0.1:{dash.port}"
            with urllib.request.urlopen(f"{base}/", timeout=5) as r:
                assert r.headers["Access-Control-Allow-Origin"] == "*"

            req = urllib.request.Request(f"{base}/", method="OPTIONS")
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 200
                assert r.headers["Access-Control-Allow-Origin"] == "*"
                methods = r.headers["Access-Control-Allow-Methods"]
                assert "OPTIONS" in methods and "GET" in methods
                assert "Content-Type" in r.headers["Access-Control-Allow-Headers"]
                assert r.headers["Access-Control-Max-Age"] == "1728000"

            # preflight for an unrouted path is still a 404
            req = urllib.request.Request(f"{base}/nope", method="OPTIONS")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=5)
            assert e.value.code == 404
        finally:
            dash.stop()


# ---------------------------------------------------------------------------
# export / import
# ---------------------------------------------------------------------------

class TestExportImport:
    def test_round_trip(self, storage):
        app_id = storage.get_meta_data_apps().insert(App(0, "ExpApp"))
        events = storage.get_events()
        events.init(app_id)
        for i in range(7):
            events.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{i}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(i)}),
                ),
                app_id,
            )
        buf = io.StringIO()
        assert export_events(storage, app_id, buf) == 7

        # import into a second app
        app2 = storage.get_meta_data_apps().insert(App(0, "ImpApp"))
        events.init(app2)
        buf.seek(0)
        assert import_events(storage, app2, buf) == 7
        imported = sorted(
            events.find(app2, filter=EventFilter()), key=lambda e: e.entity_id
        )
        assert len(imported) == 7
        assert imported[3].properties["rating"] == 3.0
        assert imported[3].target_entity_id == "i3"

    def test_malformed_line_reports_position_and_committed(self, storage):
        from predictionio_tpu.tools.export_import import ImportFormatError

        app_id = storage.get_meta_data_apps().insert(App(0, "BadApp"))
        storage.get_events().init(app_id)
        good = json.dumps({"event": "buy", "entityType": "user", "entityId": "u1"})
        buf = io.StringIO(good + "\n{not json\n")
        with pytest.raises(ImportFormatError) as e:
            import_events(storage, app_id, buf)
        assert e.value.line_no == 2

    def test_cli_import_rejects_unknown_app(self, tmp_path, monkeypatch):
        from predictionio_tpu.cli.pio import main
        from predictionio_tpu.storage.registry import Storage

        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
        Storage.reset_default()
        try:
            f = tmp_path / "in.jsonl"
            f.write_text("")
            assert main(["import", "--appid", "42", "--input", str(f)]) == 1
            assert main(["export", "--appid", "42", "--output",
                         str(tmp_path / "out.jsonl")]) == 1
        finally:
            Storage.reset_default()


# ---------------------------------------------------------------------------
# SelfCleaningDataSource
# ---------------------------------------------------------------------------

def _ev(event, entity_id, props=None, t=None):
    return Event(
        event=event,
        entity_type="user",
        entity_id=entity_id,
        properties=DataMap(props or {}),
        event_time=t or datetime(2026, 7, 1, tzinfo=timezone.utc),
    )


class _CleaningDS(SelfCleaningDataSource):
    def __init__(self, window):
        self.event_window = window


class TestSelfCleaningDataSource:
    NOW = datetime(2026, 7, 10, tzinfo=timezone.utc)

    def test_window_filter(self):
        ds = _CleaningDS(EventWindow(duration=timedelta(days=3)))
        old = _ev("buy", "u1", t=datetime(2026, 7, 1, tzinfo=timezone.utc))
        new = _ev("buy", "u2", t=datetime(2026, 7, 9, tzinfo=timezone.utc))
        assert ds.clean_events([old, new], now=self.NOW) == [new]

    def test_compress_properties(self):
        ds = _CleaningDS(EventWindow(compress_properties=True))
        e1 = _ev("$set", "u1", {"a": 1, "b": 2}, t=datetime(2026, 7, 2, tzinfo=timezone.utc))
        e2 = _ev("$set", "u1", {"b": 3, "c": 4}, t=datetime(2026, 7, 5, tzinfo=timezone.utc))
        other = _ev("buy", "u1", t=datetime(2026, 7, 3, tzinfo=timezone.utc))
        out = ds.clean_events([e1, e2, other], now=self.NOW)
        sets = [e for e in out if e.event == "$set"]
        assert len(sets) == 1
        assert sets[0].properties.fields == {"a": 1, "b": 3, "c": 4}
        assert sets[0].event_time == e2.event_time
        assert other in out

    def test_remove_duplicates(self):
        ds = _CleaningDS(EventWindow(remove_duplicates=True))
        a = _ev("buy", "u1")
        b = _ev("buy", "u1")
        c = _ev("buy", "u2")
        assert ds.clean_events([a, b, c], now=self.NOW) == [a, c]

    def test_no_window_passthrough(self):
        ds = _CleaningDS(None)
        events = [_ev("buy", "u1"), _ev("buy", "u1")]
        assert ds.clean_events(events, now=self.NOW) == events

    def test_clean_persisted(self, storage):
        app_id = storage.get_meta_data_apps().insert(App(0, "CleanApp"))
        dao = storage.get_events()
        dao.init(app_id)
        dao.insert(_ev("$set", "u1", {"a": 1}, t=datetime(2026, 7, 2, tzinfo=timezone.utc)), app_id)
        dao.insert(_ev("$set", "u1", {"a": 2}, t=datetime(2026, 7, 5, tzinfo=timezone.utc)), app_id)
        dao.insert(_ev("buy", "u2", t=datetime(2026, 7, 6, tzinfo=timezone.utc)), app_id)

        ds = _CleaningDS(EventWindow(compress_properties=True))
        assert ds.clean_persisted_events(storage, app_id, now=self.NOW) == 2
        stored = list(dao.find(app_id, filter=EventFilter()))
        assert len(stored) == 2
        merged = next(e for e in stored if e.event == "$set")
        assert merged.properties["a"] == 2


class TestBinScripts:
    """The bin/ launcher stack (role of the reference's bin/pio*,
    tools/.../console entry): pio wrapper execs the Python console;
    pio-start-all/pio-stop-all manage daemons with pidfiles."""

    def test_bin_pio_version_and_daemon_lifecycle(self, tmp_path):
        import os
        import pathlib
        import subprocess
        import time
        import urllib.request

        repo = pathlib.Path(__file__).resolve().parents[1]
        env = dict(
            os.environ,
            PIO_FS_BASEDIR=str(tmp_path),
            PIO_PID_DIR=str(tmp_path),
            PIO_LOG_DIR=str(tmp_path),
            PIO_EVENTSERVER_PORT="17172",
            PIO_DASHBOARD_PORT="19192",
            PIO_ADMINSERVER_PORT="17173",
        )
        out = subprocess.run([str(repo / "bin" / "pio"), "version"],
                             capture_output=True, text=True, env=env)
        assert out.returncode == 0 and out.stdout.strip()

        subprocess.run([str(repo / "bin" / "pio-start-all")],
                       check=True, env=env, capture_output=True)
        try:
            alive = None
            for _ in range(60):
                try:
                    with urllib.request.urlopen(
                            "http://127.0.0.1:17172/", timeout=2) as r:
                        alive = json.loads(r.read())
                    break
                except OSError:
                    time.sleep(0.5)
            assert alive == {"status": "alive"}
            assert (tmp_path / "eventserver.pid").exists()
        finally:
            stop = subprocess.run([str(repo / "bin" / "pio-stop-all")],
                                  env=env, capture_output=True, text=True)
        assert "Stopped eventserver" in stop.stdout
        assert not (tmp_path / "eventserver.pid").exists()


@pytest.mark.skipif(
    importlib.util.find_spec("pyarrow") is None,
    reason="pyarrow not installed (optional extra: predictionio-tpu[parquet])",
)
class TestParquetExportImport:
    """Parquet format option (EventsToFile.scala:97-105). Properties are
    a JSON-string column (documented divergence from Spark's inferred
    struct); everything else round-trips field-for-field."""

    def _ingest(self, storage, name, n=7):
        app_id = storage.get_meta_data_apps().insert(App(0, name))
        events = storage.get_events()
        events.init(app_id)
        for i in range(n):
            events.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{i}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(i), "tags_n": i}),
                    tags=("a", f"t{i}") if i % 2 else (),
                    pr_id="pr1" if i == 3 else None,
                ),
                app_id,
            )
        return app_id

    def test_round_trip_identical(self, storage, tmp_path):
        from predictionio_tpu.tools.export_import import (
            export_events_parquet,
            import_events_parquet,
        )

        app_id = self._ingest(storage, "PqApp")
        path = str(tmp_path / "events.parquet")
        assert export_events_parquet(storage, app_id, path) == 7

        app2 = storage.get_meta_data_apps().insert(App(0, "PqApp2"))
        events = storage.get_events()
        events.init(app2)
        assert import_events_parquet(storage, app2, path) == 7

        src = sorted(events.find(app_id, filter=EventFilter()),
                     key=lambda e: e.entity_id)
        dst = sorted(events.find(app2, filter=EventFilter()),
                     key=lambda e: e.entity_id)
        for a, b in zip(src, dst):
            assert a.event == b.event
            assert a.entity_id == b.entity_id
            assert a.target_entity_id == b.target_entity_id
            assert dict(a.properties) == dict(b.properties)
            assert tuple(a.tags) == tuple(b.tags)
            assert a.pr_id == b.pr_id
            # wire format carries millisecond precision (reference joda
            # ISO-8601 millis; same truncation as the json path)
            assert a.event_time.replace(
                microsecond=a.event_time.microsecond // 1000 * 1000
            ) == b.event_time

    def test_cli_parquet_round_trip(self, tmp_path, monkeypatch):
        from predictionio_tpu.cli.pio import main
        from predictionio_tpu.storage.registry import Storage

        env = {
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        Storage.reset_default()
        try:
            storage = Storage.default()
            app_id = self._ingest(storage, "CliPq", n=5)
            out = str(tmp_path / "ev.parquet")
            assert main(["export", "--appid", str(app_id), "--output", out,
                         "--format", "parquet"]) == 0
            app2 = storage.get_meta_data_apps().insert(App(0, "CliPq2"))
            storage.get_events().init(app2)
            # format inferred from .parquet extension
            assert main(["import", "--appid", str(app2), "--input", out]) == 0
            got = list(storage.get_events().find(app2, filter=EventFilter()))
            assert len(got) == 5
        finally:
            Storage.reset_default()
