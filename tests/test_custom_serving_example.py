"""Scenario test for examples/recommendation-custom-serving — the
custom-serving variant (reference:
examples/scala-parallel-recommendation/custom-serving): a user-defined
Serving with its own params filters disabled items at serve time, with
the disabled file re-read per query (live control)."""

import os
import sys

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.context import EngineContext
from predictionio_tpu.workflow.persistence import load_models
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples", "recommendation-custom-serving"
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    # the example module is literally named "engine"; evict any stale one
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


@pytest.fixture
def storage_with_ratings(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, "CustomServingApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(5)
    for u in range(16):
        for i in range(12):
            if i % 2 == u % 2 and rng.random() < 0.9:
                events.insert(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": 5.0}),
                    ),
                    app_id,
                )
    return storage


def test_shipped_engine_json_binds(example_engine):
    """The engine.json shipped with the example must bind as-is — it uses
    the reference templates' camelCase param names (numIterations,
    lambda), which map onto the snake_case dataclass fields."""
    import json

    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        variant = json.load(f)
    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    algo_params = ep.algorithm_params_list[0][1]
    assert algo_params.num_iterations == 10
    assert algo_params.lambda_ == 0.01
    assert ep.serving_params[1].filepath == "disabled.txt"


def test_serve_time_filtering_live(example_engine, storage_with_ratings,
                                   tmp_path, monkeypatch):
    from predictionio_tpu.templates.recommendation import Query

    disabled_file = tmp_path / "disabled.txt"
    variant = {
        "id": "custom-serving",
        "engineFactory": "engine.engine_factory",
        "datasource": {"params": {"app_name": "CustomServingApp"}},
        "algorithms": [
            {"name": "als",
             "params": {"rank": 8, "num_iterations": 8, "lambda_": 0.05,
                        "seed": 1, "use_mesh": False}}
        ],
        "serving": {"params": {"filepath": str(disabled_file)}},
    }
    storage = storage_with_ratings
    outcome = run_train(variant=variant, storage=storage)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=storage)
    models = eng.prepare_deploy(ctx, ep, load_models(storage, outcome.instance_id))
    _, _, algos, serving = eng.make_components(ep)
    assert isinstance(serving, example_engine.DisabledItemsServing)

    def ask(user="u0", num=5):
        q = serving.supplement(Query(user=user, num=num))
        return serving.serve(q, [a.predict(m, q) for a, m in zip(algos, models)])

    # no disabled file yet: normal recommendations
    first = ask()
    assert len(first.item_scores) > 0
    target = first.item_scores[0].item

    # disable the top item; next query (same deployed model) drops it
    disabled_file.write_text(f"{target}\n")
    filtered = ask()
    assert target not in [s.item for s in filtered.item_scores]
    assert len(filtered.item_scores) >= len(first.item_scores) - 1

    # live re-enable: clearing the file restores it without redeploy
    disabled_file.write_text("")
    again = ask()
    assert target in [s.item for s in again.item_scores]
