"""Ring attention vs full attention numerics on a virtual device mesh."""

from __future__ import annotations

import numpy as np
import pytest

from predictionio_tpu.utils.testing import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from predictionio_tpu.ops.attention import (  # noqa: E402
    blockwise_attention,
    full_attention,
    ring_attention,
)

B, H, S, D = 2, 4, 64, 16  # S divides the 8-device seq axis


def _qkv(seed: int = 0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    shape = (B, H, S, D)
    q = jnp.asarray(rng.standard_normal(shape), dtype=dtype)
    k = jnp.asarray(rng.standard_normal(shape), dtype=dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype=dtype)
    return q, k, v


@pytest.fixture(scope="module")
def seq_mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("seq",))


class TestRingAttention:
    def test_matches_full_causal(self, seq_mesh):
        q, k, v = _qkv()
        expected = full_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, seq_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=1e-5, rtol=1e-5)

    def test_matches_full_noncausal(self, seq_mesh):
        q, k, v = _qkv(1)
        expected = full_attention(q, k, v, causal=False)
        got = ring_attention(q, k, v, seq_mesh, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=1e-5, rtol=1e-5)

    def test_padding_mask(self, seq_mesh):
        q, k, v = _qkv(2)
        # second sequence only has 40 real positions
        kv_mask = np.ones((B, S), dtype=np.float32)
        kv_mask[1, 40:] = 0.0
        kv_mask = jnp.asarray(kv_mask)
        expected = full_attention(q, k, v, causal=True, kv_mask=kv_mask)
        got = ring_attention(q, k, v, seq_mesh, causal=True, kv_mask=kv_mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=1e-5, rtol=1e-5)

    def test_inside_jit_with_sharded_inputs(self, seq_mesh):
        q, k, v = _qkv(3)
        sh = NamedSharding(seq_mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

        @jax.jit
        def run(q, k, v):
            return ring_attention(q, k, v, seq_mesh, causal=True)

        got = run(qs, ks, vs)
        expected = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=1e-5, rtol=1e-5)

    def test_bf16_inputs_accumulate_f32(self, seq_mesh):
        q, k, v = _qkv(4, dtype=jnp.bfloat16)
        expected = full_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, seq_mesh, causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32),
            np.asarray(expected, dtype=np.float32),
            atol=3e-2, rtol=3e-2,
        )

    def test_grads_flow(self, seq_mesh):
        q, k, v = _qkv(5)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True) ** 2)

        def loss_full(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring)(q, k, v)
        g_full = jax.grad(loss_full)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                                   atol=1e-4, rtol=1e-4)


class TestBlockwiseAttention:
    """Single-device long-context training path: query-tile scan +
    remat — must match full_attention in values AND gradients."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_values(self, causal):
        q, k, v = _qkv(11)
        kv_mask = np.ones((B, S), dtype=np.float32)
        kv_mask[1, 40:] = 0.0
        kv_mask = jnp.asarray(kv_mask)
        exp = full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
        got = blockwise_attention(q, k, v, causal=causal, kv_mask=kv_mask,
                                  q_block=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=1e-5, rtol=1e-5)

    def test_matches_full_gradients(self):
        q, k, v = _qkv(12)

        def loss_full(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        def loss_block(q, k, v):
            return jnp.sum(
                blockwise_attention(q, k, v, causal=True, q_block=16) ** 2)

        gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gb):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-4, rtol=1e-4)

    def test_q_block_must_divide(self):
        q, k, v = _qkv(13)
        with pytest.raises(ValueError, match="divide"):
            blockwise_attention(q, k, v, q_block=48)

    def test_seqrec_training_routes_blockwise_at_long_s(self, monkeypatch):
        """forward() must take the blockwise path at S >= 4096 (stubbed —
        the point is routing; the math is covered above)."""
        from predictionio_tpu.models import seqrec

        calls = []
        monkeypatch.setattr(
            seqrec, "blockwise_attention",
            lambda q, k, v, **kw: calls.append(kw["q_block"]) or q,
        )
        cfg = seqrec.SeqRecConfig(vocab=50, max_len=4096, d_model=8,
                                  n_heads=2, n_layers=1)
        params = seqrec.init_params(jax.random.PRNGKey(0), cfg)
        seqs = jnp.ones((1, 4096), jnp.int32)
        seqrec.forward(params, seqs, cfg)
        # smallest dividing tile: the r5 sweep measured q_block=128
        # 1.8x faster than 512 at S=4096
        assert calls == [128]


class TestPallasFlashAttention:
    """Pallas flash kernel — auto-dispatched for causal compiled-mode
    calls in the measured 2048<=S<=16384 envelope since the round-5
    causal-KV-skip + tile-sweep pass (ops/pallas_attention docstring
    has the A/B table); on the CPU test backend force=True exercises
    it in interpret mode."""

    def test_matches_full_attention(self):
        from predictionio_tpu.ops.pallas_attention import flash_attention

        q, k, v = _qkv(6)
        kv_mask = np.ones((B, S), dtype=np.float32)
        kv_mask[0, 50:] = 0.0
        kv_mask = jnp.asarray(kv_mask)
        for causal in (True, False):
            exp = full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
            got = flash_attention(q, k, v, causal=causal, kv_mask=kv_mask,
                                  force=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                       atol=1e-5, rtol=1e-5)

    def test_default_path_is_xla_on_cpu(self):
        """Interpret mode (CPU backend) never auto-engages — unforced
        calls are exactly full_attention regardless of S."""
        from predictionio_tpu.ops import pallas_attention

        q, k, v = _qkv(7)
        got = pallas_attention.flash_attention(q, k, v, causal=True)
        exp = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=1e-6, rtol=1e-6)

    def test_auto_dispatch_causal_envelope(self, monkeypatch):
        """r5 dispatch rules: unforced compiled-mode calls engage the
        kernel ONLY for causal attention inside the measured
        2048<=S<=16384 envelope; non-causal and out-of-envelope depths
        fall back; force=True routes anywhere buildable (mode and
        kernel stubbed — no TPU in CI; the point is routing)."""
        from predictionio_tpu.ops import pallas_attention as pa

        calls = []
        monkeypatch.setattr(pa, "_mode", lambda: "compiled")
        monkeypatch.setattr(
            pa, "_flash_call",
            lambda q, k, v, m, causal, interp, *t: calls.append(q.shape) or q,
        )
        # stub the fallback too: at these sizes the real full_attention
        # would materialize (S, S) logits (~4 GB at 32768)
        monkeypatch.setattr(pa, "full_attention",
                            lambda q, k, v, **kw: q)
        for S, causal, expect in (
            (1024, True, 0),      # below the envelope
            (2048, True, 1),      # measured win
            (4096, True, 1),
            (16384, True, 1),     # envelope top
            (32768, True, 0),     # beyond VMEM-resident K/V
            (4096, False, 0),     # non-causal: the KV-skip win is causal-only
        ):
            calls.clear()
            q = jnp.zeros((1, 1, S, 8), jnp.float32)
            pa.flash_attention(q, q, q, causal=causal)
            assert len(calls) == expect, (S, causal)
        for S, expect in ((2048, 1), (16384, 1)):
            calls.clear()
            q = jnp.zeros((1, 1, S, 8), jnp.float32)
            pa.flash_attention(q, q, q, causal=True, force=True)
            assert len(calls) == expect, (S, expect)
