"""Event model + validation + JSON wire codec tests.

Validation rules per reference Event.scala:113-143; wire format per
EventJson4sSupport.scala.
"""

from datetime import datetime, timezone

import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event, EventValidation, EventValidationError
from predictionio_tpu.core.json_codec import (
    event_from_json,
    event_to_json,
    format_datetime,
    parse_datetime,
)


def ok(**kw):
    e = Event(**{"event": "rate", "entity_type": "user", "entity_id": "u1", **kw})
    EventValidation.validate(e)
    return e


def bad(**kw):
    with pytest.raises(EventValidationError):
        ok(**kw)


def test_minimal_valid_event():
    e = ok()
    assert e.event_time.tzinfo is not None  # normalized to aware UTC


def test_empty_fields_rejected():
    bad(event="")
    bad(entity_type="")
    bad(entity_id="")
    bad(target_entity_type="", target_entity_id="i1")
    bad(target_entity_type="item", target_entity_id="")


def test_target_entity_must_be_paired():
    bad(target_entity_type="item")
    bad(target_entity_id="i1")
    ok(target_entity_type="item", target_entity_id="i1")


def test_special_events():
    ok(event="$set", properties=DataMap({"a": 1}))
    ok(event="$set")  # $set with empty properties is allowed
    ok(event="$unset", properties=DataMap({"a": 1}))
    bad(event="$unset")  # $unset requires non-empty properties
    ok(event="$delete")


def test_reserved_prefixes():
    bad(event="$custom")
    bad(event="pio_thing")
    bad(entity_type="pio_user")
    ok(entity_type="pio_pr")  # built-in entity type allowed
    bad(target_entity_type="pio_x", target_entity_id="i")
    ok(target_entity_type="pio_pr", target_entity_id="i")


def test_special_event_cannot_have_target():
    bad(event="$set", target_entity_type="item", target_entity_id="i1")


def test_reserved_property_names():
    bad(properties=DataMap({"pio_score": 1}))
    bad(properties=DataMap({"$weird": 1}))
    ok(properties=DataMap({"score": 1}))


def test_datetime_roundtrip():
    t = datetime(2004, 12, 13, 21, 39, 45, 618000, tzinfo=timezone.utc)
    s = format_datetime(t)
    assert s == "2004-12-13T21:39:45.618Z"
    assert parse_datetime(s) == t
    # offset form parses too
    assert parse_datetime("2004-12-13T21:39:45.618-07:00").utcoffset().total_seconds() == -7 * 3600


def test_json_roundtrip():
    e = ok(
        event="buy",
        target_entity_type="item",
        target_entity_id="i1",
        properties=DataMap({"price": 9.99, "tags": ["x"]}),
        event_time=datetime(2020, 5, 1, 12, 0, 0, 123000, tzinfo=timezone.utc),
        tags=["t1", "t2"],
        pr_id="pr-1",
        creation_time=datetime(2020, 5, 1, 12, 0, 1, 456000, tzinfo=timezone.utc),
        event_id="e-42",
    )
    j = event_to_json(e)
    assert j["event"] == "buy"
    assert j["entityType"] == "user"
    assert j["eventTime"] == "2020-05-01T12:00:00.123Z"
    e2 = event_from_json(j)
    assert e2 == e


def test_json_defaults_and_validation():
    e = event_from_json({"event": "view", "entityType": "user", "entityId": "u9"})
    assert e.properties.is_empty() and e.tags == ()
    with pytest.raises(EventValidationError):
        event_from_json({"event": "view", "entityType": "user"})  # no entityId
    with pytest.raises(EventValidationError):
        event_from_json(
            {"event": "$unset", "entityType": "user", "entityId": "u1", "properties": {}}
        )
    with pytest.raises(EventValidationError):
        event_from_json(
            {"event": "view", "entityType": "user", "entityId": "u1", "eventTime": "not-a-time"}
        )
