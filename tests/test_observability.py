"""Observability plane (docs/observability.md): tracing, log-bucketed
histograms, the metric registry, Prometheus text rendering, `GET
/metrics` on both servers, request ids, structured access logs, the
windowed ingest rate, and — the invariant that motivates the whole
layer — torn-free concurrent scrapes under live traffic.

The Prometheus round-trip uses the small in-test parser below: the
exporter's output contract is pinned by parsing it back, not by string
golden-files.
"""

from __future__ import annotations

import contextvars
import http.client
import json
import logging
import re
import threading
import time
import urllib.request

import pytest

from predictionio_tpu.api.event_server import EventServer, EventServerConfig
from predictionio_tpu.api.stats import IngestStats
from predictionio_tpu.obs.histogram import LatencyHistogram
from predictionio_tpu.obs.registry import (
    HistogramFamily,
    Metric,
    MetricRegistry,
)
from predictionio_tpu.obs.exporter import render_prometheus
from predictionio_tpu.obs.trace import (
    Trace,
    TraceLog,
    active_trace,
    span,
    use_trace,
)
from predictionio_tpu.storage.base import AccessKey, App
from predictionio_tpu.utils.testing import memory_storage

pytestmark = pytest.mark.obs


# ---------------------------------------------------------------------------
# a small Prometheus text parser — the round-trip half of the exporter
# contract (tests parse what the server exposes; golden strings rot)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape(value: str) -> str:
    """Single-pass label-value unescape (text-format 0.0.4). The old
    sequential ``str.replace`` chain re-scanned bytes produced by
    earlier passes, so a value holding a LITERAL backslash before 'n'
    (``a\\nb``) came back with a real newline — pinned by the
    round-trip test with hostile values in test_fleet_obs.py."""
    return _UNESCAPE_RE.sub(
        lambda m: {"n": "\n", '"': '"', "\\": "\\"}.get(
            m.group(1), m.group(1)), value)


def parse_prometheus(text: str) -> dict:
    """{family: {"type": ..., "help": ..., "samples":
    {(sample_name, frozen_labels): float}}} — raises on any line that
    is not HELP/TYPE/sample, which IS the validity assertion."""
    families: dict[str, dict] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {"samples": {}})["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram"), line
            families.setdefault(name, {"samples": {}})["type"] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = tuple(sorted(
            (k, _unescape(v))
            for k, v in _LABEL_RE.findall(m.group("labels") or "")))
        value = float(m.group("value")) if m.group("value") != "NaN" \
            else float("nan")
        sample_name = m.group("name")
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if family.endswith(suffix) and family[:-len(suffix)] in families:
                family = family[:-len(suffix)]
                break
        assert family in families, f"sample before HELP/TYPE: {line!r}"
        families[family]["samples"][(sample_name, labels)] = value
    for name, fam in families.items():
        assert "type" in fam and "help" in fam, f"{name}: missing HELP/TYPE"
        if fam["type"] == "counter":
            assert name.endswith("_total"), \
                f"counter {name} must end in _total"
    return families


def check_histogram_consistency(families: dict, name: str) -> None:
    """Per label set: buckets cumulative and monotone, +Inf == _count."""
    fam = families[name]
    assert fam["type"] == "histogram"
    by_labels: dict[tuple, dict[str, float]] = {}
    counts: dict[tuple, float] = {}
    for (sample, labels), value in fam["samples"].items():
        base = tuple(kv for kv in labels if kv[0] != "le")
        if sample == f"{name}_bucket":
            le = dict(labels)["le"]
            by_labels.setdefault(base, {})[le] = value
        elif sample == f"{name}_count":
            counts[base] = value
    assert by_labels, f"{name}: no buckets"
    for base, buckets in by_labels.items():
        assert "+Inf" in buckets, f"{name}{base}: no +Inf bucket"
        finite = sorted(
            ((float(le), v) for le, v in buckets.items() if le != "+Inf"))
        values = [v for _, v in finite] + [buckets["+Inf"]]
        assert values == sorted(values), \
            f"{name}{base}: non-monotone buckets {values}"
        assert buckets["+Inf"] == counts[base], \
            f"{name}{base}: +Inf {buckets['+Inf']} != count {counts[base]}"


# ---------------------------------------------------------------------------
# histogram + registry units
# ---------------------------------------------------------------------------

class TestLatencyHistogram:
    def test_buckets_and_overflow(self):
        h = LatencyHistogram(bounds=(0.001, 0.01, 0.1))
        h.observe(0.0005)     # <= 0.001
        h.observe(0.005)      # <= 0.01
        h.observe_many([0.05, 5.0])   # <= 0.1, overflow
        s = h.snapshot()
        assert s.cumulative == (1, 2, 3, 4)
        assert s.count == 4 and s.cumulative[-1] == 4
        assert abs(s.sum - 5.0555) < 1e-9

    def test_quantiles_saturate_at_top_bound(self):
        h = LatencyHistogram(bounds=(0.001, 0.01))
        for _ in range(99):
            h.observe(0.0005)
        h.observe(10.0)  # overflow
        s = h.snapshot()
        assert s.quantile(0.5) == 0.001
        assert s.quantile(0.999) == 0.01  # saturates, never invents
        assert s.summary_ms()["count"] == 100

    def test_rejects_unsorted_bounds(self):
        with pytest.raises(ValueError):
            LatencyHistogram(bounds=(0.1, 0.01))

    def test_concurrent_observe_loses_nothing(self):
        h = LatencyHistogram()
        n, threads = 2000, 8

        def work():
            for i in range(n):
                h.observe(0.0001 * (i % 50))

        ts = [threading.Thread(target=work) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        s = h.snapshot()
        assert s.count == n * threads == s.cumulative[-1]


class TestRegistry:
    def test_merge_and_kind_conflict(self):
        reg = MetricRegistry()
        reg.register(lambda: [Metric("pio_x_total", "counter", "x",
                                     samples=[({}, 1.0)])])
        reg.register(lambda: [Metric("pio_x_total", "counter", "x",
                                     samples=[({"a": "b"}, 2.0)])])
        merged = {m.name: m for m in reg.collect()}
        assert len(merged["pio_x_total"].samples) == 2
        reg.register(lambda: [Metric("pio_x_total", "gauge", "x")])
        with pytest.raises(ValueError):
            reg.collect()

    def test_histogram_family_fallback_label(self):
        fam = HistogramFamily("pio_t_seconds", "t", "route", ("a",))
        fam.observe("a", 0.01)
        fam.observe("zzz-unknown", 0.01)   # folds into "other"
        (metric,) = fam.collect()
        labels = {dict(ls)["route"]: snap.count
                  for ls, snap in metric.histograms}
        assert labels == {"a": 1, "other": 1}

    def test_render_round_trip_with_escaping(self):
        reg = MetricRegistry()
        h = LatencyHistogram(bounds=(0.001, 1.0))
        h.observe(0.5)
        reg.register(lambda: [
            Metric("pio_demo_total", "counter", "help with \\ backslash",
                   samples=[({"k": 'va"l\nue'}, 3.0)]),
            Metric("pio_demo_seconds", "histogram", "hist",
                   histograms=[({"route": "q"}, h.snapshot())]),
        ])
        families = parse_prometheus(render_prometheus(reg))
        assert families["pio_demo_total"]["samples"][
            ("pio_demo_total", (("k", 'va"l\nue'),))] == 3.0
        check_histogram_consistency(families, "pio_demo_seconds")


# ---------------------------------------------------------------------------
# tracing units
# ---------------------------------------------------------------------------

class TestTrace:
    def test_ambient_span_noop_without_trace(self):
        assert active_trace() is None
        with span("nothing"):     # the disabled path: shared no-op
            pass

    def test_spans_and_external_intervals(self):
        t = Trace("req", request_id="r1")
        with use_trace(t):
            with span("a"):
                pass
        t.add_span("queue_wait", 1.0, 1.25)   # dispatcher-style record
        t.finish(status=200)
        doc = t.to_dict()
        names = [s["name"] for s in doc["spans"]]
        assert "a" in names and "queue_wait" in names
        qw = next(s for s in doc["spans"] if s["name"] == "queue_wait")
        assert qw["durationMs"] == 250.0
        assert doc["requestId"] == "r1" and doc["tags"] == {"status": 200}

    def test_contextvar_survives_copy_context(self):
        """The deadline-dispatch pool runs queries under
        contextvars.copy_context(); spans opened there must land on
        the caller's trace."""
        t = Trace("req")
        with use_trace(t):
            ctx = contextvars.copy_context()
        result = []

        def work():
            result.append(active_trace())
            with span("pooled"):
                pass

        th = threading.Thread(target=lambda: ctx.run(work))
        th.start()
        th.join()
        assert result == [t]
        assert [s["name"] for s in t.to_dict()["spans"]] == ["pooled"]

    def test_trace_log_is_bounded(self):
        log = TraceLog(maxlen=4)
        for i in range(10):
            tr = Trace(f"t{i}")
            tr.finish()
            log.record(tr)
        snap = log.snapshot()
        assert len(snap) == 4 and log.recorded == 10
        assert snap[0]["name"] == "t9"   # newest first


# ---------------------------------------------------------------------------
# ingest windowed rate (the EWMA closed-loop-bias fix)
# ---------------------------------------------------------------------------

class ManualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestIngestWindowedRate:
    def test_windowed_rate_counts_complete_seconds(self):
        clock = ManualClock()
        stats = IngestStats(clock=clock)
        assert stats.snapshot()["eventsPerSecWindowed"] is None
        for _ in range(5):
            stats.record_batch(10)      # 50 events in second 1000
        clock.t = 1001.5
        stats.record_batch(30)          # partial second 1001 (excluded)
        snap = stats.snapshot()
        # window = [1000, 1001): 50 events over 1 complete second
        assert snap["eventsPerSecWindowed"] == 50.0
        assert snap["windowSeconds"] == 1
        clock.t = 1004.0
        snap = stats.snapshot()
        # window = [1000, 1004): 80 events over 4 seconds
        assert snap["eventsPerSecWindowed"] == 20.0

    def test_stale_buckets_age_out(self):
        clock = ManualClock()
        stats = IngestStats(clock=clock)
        stats.record_batch(1000)
        clock.t += 200.0                # far past WINDOW_SECONDS
        stats.record_batch(59)
        clock.t += 1.0
        snap = stats.snapshot()
        # only the recent second is in the window; the old burst aged out
        assert snap["eventsPerSecWindowed"] == pytest.approx(1.0)
        # ...while the EWMA still carries closed-loop history
        assert snap["events"] == 1059

    def test_windowed_rate_is_not_issue_rate_biased(self):
        """The documented EWMA caveat: a closed-loop generator that
        pauses between bursts drags the EWMA; the ring reports what
        actually landed per wall second."""
        clock = ManualClock()
        stats = IngestStats(clock=clock)
        for _ in range(10):
            stats.record_batch(100)     # burst: 1000 events in 1s
            clock.t += 0.1
        clock.t += 1.0                  # generator think-time
        ewma = stats.snapshot()["eventsPerSecEwma"]
        windowed = stats.snapshot()["eventsPerSecWindowed"]
        assert windowed == pytest.approx(500.0)   # 1000 over 2 seconds
        assert ewma == pytest.approx(1000.0, rel=0.2)


# ---------------------------------------------------------------------------
# servers end to end
# ---------------------------------------------------------------------------

EVENT = {"event": "rate", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 5}}


@pytest.fixture
def event_server():
    storage = memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(0, "obsapp"))
    storage.get_meta_data_access_keys().insert(AccessKey("k", app_id, ()))
    storage.get_events().init(app_id)
    srv = EventServer(storage, EventServerConfig(
        ip="127.0.0.1", port=0, stats=True, tracing=True, access_log=True))
    srv.start()
    yield srv
    srv.stop()


def _http(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=20)
    payload = json.dumps(body) if body is not None else None
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    conn.request(method, path, body=payload, headers=hdrs)
    resp = conn.getresponse()
    raw = resp.read()
    out = (resp.status, raw, dict(resp.getheaders()))
    conn.close()
    return out


class TestEventServerObservability:
    def test_metrics_exposes_ingest_and_resilience(self, event_server):
        port = event_server.port
        assert _http(port, "POST", "/events.json?accessKey=k", EVENT)[0] == 201
        assert _http(port, "POST", "/batch/events.json?accessKey=k",
                     [EVENT, EVENT])[0] == 200
        status, raw, headers = _http(port, "GET", "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        families = parse_prometheus(raw.decode())
        samples = families["pio_ingest_events_total"]["samples"]
        assert samples[("pio_ingest_events_total", ())] == 3.0
        assert families["pio_ingest_batches_total"]["samples"][
            ("pio_ingest_batches_total", ())] == 2.0
        check_histogram_consistency(families, "pio_ingest_batch_size")
        check_histogram_consistency(families, "pio_ingest_insert_seconds")
        check_histogram_consistency(families, "pio_http_request_seconds")
        assert ("pio_server_info",
                (("server", "event"),
                 ("version", __import__("predictionio_tpu").__version__))
                ) in families["pio_server_info"]["samples"]

    def test_ingest_traces_split_validate_from_insert(self, event_server):
        port = event_server.port
        _http(port, "POST", "/batch/events.json?accessKey=k", [EVENT])
        # traces carry per-request data (unlike the aggregate-only
        # /metrics) — the accessKey auth every event route uses applies
        assert _http(port, "GET", "/traces.json")[0] == 401
        status, raw, _ = _http(port, "GET", "/traces.json?accessKey=k")
        assert status == 200
        doc = json.loads(raw)
        assert doc["tracing"] is True
        batch = next(t for t in doc["traces"]
                     if t["name"] == "batch/events.json")
        names = [s["name"] for s in batch["spans"]]
        assert names == ["parse", "validate", "insert_batch"]

    def test_request_id_echoed_and_propagated(self, event_server):
        port = event_server.port
        # inbound well-formed id is echoed verbatim
        _, _, headers = _http(port, "GET", "/",
                              headers={"X-PIO-Request-Id": "corr-42"})
        assert headers["X-PIO-Request-Id"] == "corr-42"
        # malformed id is replaced, not propagated (log injection)
        _, _, headers = _http(port, "GET", "/",
                              headers={"X-PIO-Request-Id": 'bad id "x"'})
        rid = headers["X-PIO-Request-Id"]
        assert rid != 'bad id "x"' and re.match(r"^[0-9a-f]{16}$", rid)

    def test_structured_access_log(self, event_server):
        # capture on the pio.access logger directly: the lazily
        # attached default handler may have turned propagation off, so
        # caplog's root-logger capture is not guaranteed to see it
        captured: list[logging.LogRecord] = []

        class Capture(logging.Handler):
            def emit(self, record):
                captured.append(record)

        handler = Capture(level=logging.INFO)
        access = logging.getLogger("pio.access")
        access.addHandler(handler)
        try:
            _http(event_server.port, "POST", "/events.json?accessKey=k",
                  EVENT, headers={"X-PIO-Request-Id": "log-me"})
            # the access line is emitted AFTER the response is written:
            # the client can observe the 201 before the handler thread
            # reaches the logger (reliably so on a 1-core host), so
            # poll with a deadline instead of racing the removeHandler
            deadline = time.monotonic() + 10.0
            entry = None
            while entry is None and time.monotonic() < deadline:
                records = [json.loads(r.getMessage()) for r in list(captured)]
                entry = next(
                    (r for r in records if r["request_id"] == "log-me"),
                    None)
                if entry is None:
                    time.sleep(0.02)
        finally:
            access.removeHandler(handler)
        assert entry is not None, "access-log line never emitted"
        assert entry["method"] == "POST"
        assert entry["path"] == "/events.json"
        assert entry["status"] == 201
        assert entry["latency_ms"] > 0
        assert entry["server"] == "event"

    def test_stats_json_carries_windowed_rate_fields(self, event_server):
        port = event_server.port
        _http(port, "POST", "/events.json?accessKey=k", EVENT)
        status, raw, _ = _http(port, "GET", "/stats.json?accessKey=k")
        assert status == 200
        ingest = json.loads(raw)["ingest"]
        assert "eventsPerSecWindowed" in ingest
        assert "windowSeconds" in ingest
        assert ingest["insertLatency"]["count"] == 1


@pytest.fixture
def engine_server(storage):
    from predictionio_tpu.api.engine_server import create_engine_server
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.workflow.deploy import ServerConfig
    from predictionio_tpu.workflow.train import run_train

    from tests.sample_engine import AlgoParams, DSParams

    params = EngineParams.of(
        data_source=DSParams(id=7, n_train=5),
        algorithms=[("sample", AlgoParams(id=0, mult=2))])
    run_train(engine_factory="tests.sample_engine.engine_factory",
              engine_params=params, variant={"id": "sample-engine"},
              storage=storage)
    server = create_engine_server(storage=storage, config=ServerConfig(
        ip="127.0.0.1", port=0, batching=True, batch_max=8,
        batch_wait_ms=5.0, cache_enabled=True, tracing=True))
    server.start()
    yield server
    server.stop()


def _post_query(port, payload, headers=None):
    return _http(port, "POST", "/queries.json", payload, headers)


class TestEngineServerObservability:
    def test_metrics_exposes_serving_counters_and_histograms(
            self, engine_server):
        port = engine_server.port
        for i in range(4):
            assert _post_query(port, {"x": i})[0] == 200
        status, raw, headers = _http(port, "GET", "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        families = parse_prometheus(raw.decode())
        get = lambda n: families[n]["samples"][(n, ())]  # noqa: E731
        assert get("pio_serving_dispatches_total") >= 1
        assert get("pio_serving_batched_queries_total") == 4
        for name in ("pio_serving_batch_size",
                     "pio_serving_queue_wait_seconds",
                     "pio_serving_device_dispatch_seconds",
                     "pio_http_request_seconds"):
            check_histogram_consistency(families, name)
        # queue-wait and device-dispatch were actually fed
        assert families["pio_serving_queue_wait_seconds"]["samples"][
            ("pio_serving_queue_wait_seconds_count", ())] == 4

    def test_query_trace_splits_queue_wait_from_device_time(
            self, engine_server):
        """The acceptance-criterion trace: one /queries.json trace
        carries distinct queue-wait and device-dispatch spans."""
        port = engine_server.port
        status, _, headers = _post_query(
            port, {"x": 41}, headers={"X-PIO-Request-Id": "trace-me"})
        assert status == 200
        assert headers["X-PIO-Request-Id"] == "trace-me"
        trace_id = headers["X-PIO-Trace-Id"]
        _, raw, _ = _http(port, "GET", "/traces.json")
        doc = json.loads(raw)
        trace = next(t for t in doc["traces"] if t["traceId"] == trace_id)
        assert trace["requestId"] == "trace-me"
        assert trace["tags"]["status"] == 200
        spans = {s["name"]: s for s in trace["spans"]}
        for name in ("parse", "bind", "codec_key", "cache_lookup",
                     "batcher.queue_wait", "batcher.device_dispatch",
                     "encode"):
            assert name in spans, f"missing span {name}: {sorted(spans)}"
        qw, dd = spans["batcher.queue_wait"], spans["batcher.device_dispatch"]
        # the split: wait ends where the dispatch starts, both measured
        assert qw["startMs"] < dd["startMs"]
        assert qw["startMs"] + qw["durationMs"] == pytest.approx(
            dd["startMs"], abs=0.5)
        assert trace["durationMs"] >= dd["durationMs"]

    def test_cache_hit_trace_has_no_dispatch_span(self, engine_server):
        port = engine_server.port
        assert _post_query(port, {"x": 7})[0] == 200
        status, _, headers = _post_query(port, {"x": 7})   # cache hit
        assert status == 200
        _, raw, _ = _http(port, "GET", "/traces.json")
        doc = json.loads(raw)
        hit = next(t for t in doc["traces"]
                   if t["traceId"] == headers["X-PIO-Trace-Id"])
        names = [s["name"] for s in hit["spans"]]
        assert "cache_lookup" in names
        assert "batcher.device_dispatch" not in names

    def test_tracing_disabled_emits_nothing(self, storage):
        from predictionio_tpu.api.engine_server import create_engine_server
        from predictionio_tpu.controller import EngineParams
        from predictionio_tpu.workflow.deploy import ServerConfig
        from predictionio_tpu.workflow.train import run_train

        from tests.sample_engine import AlgoParams, DSParams

        run_train(
            engine_factory="tests.sample_engine.engine_factory",
            engine_params=EngineParams.of(
                data_source=DSParams(id=7, n_train=5),
                algorithms=[("sample", AlgoParams(id=0, mult=2))]),
            variant={"id": "sample-engine"}, storage=storage)
        server = create_engine_server(storage=storage, config=ServerConfig(
            ip="127.0.0.1", port=0, tracing=False))
        server.start()
        try:
            port = server.port
            status, _, headers = _post_query(port, {"x": 1})
            assert status == 200
            assert "X-PIO-Trace-Id" not in headers
            assert "X-PIO-Request-Id" in headers
            _, raw, _ = _http(port, "GET", "/traces.json")
            doc = json.loads(raw)
            assert doc == {"tracing": False, "traces": []}
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# the concurrency hammer: scrapes must never tear under live traffic
# ---------------------------------------------------------------------------

class TestConcurrentScrapes:
    SCRAPES = 25

    def test_metrics_and_stats_under_live_traffic(self, engine_server):
        """Hammer /metrics and /stats.json from threads while query
        traffic flows: every exposition parses, histograms stay
        internally consistent, counters are monotone scrape-over-scrape."""
        port = engine_server.port
        stop = threading.Event()
        errors: list[BaseException] = []

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    st, _, _ = _post_query(port, {"x": i % 16})
                    assert st == 200
                except Exception as e:   # noqa: BLE001
                    errors.append(e)
                    return
                i += 1

        monotone_keys = [
            ("pio_serving_batched_queries_total",
             "pio_serving_batched_queries_total", ()),
            ("pio_serving_dispatches_total",
             "pio_serving_dispatches_total", ()),
        ]

        def scraper():
            last: dict = {}
            try:
                for _ in range(self.SCRAPES):
                    st, raw, _ = _http(port, "GET", "/metrics")
                    assert st == 200
                    families = parse_prometheus(raw.decode())
                    for name in ("pio_serving_queue_wait_seconds",
                                 "pio_serving_device_dispatch_seconds",
                                 "pio_serving_batch_size",
                                 "pio_http_request_seconds"):
                        check_histogram_consistency(families, name)
                    for fam, sample, labels in monotone_keys:
                        value = families[fam]["samples"][(sample, labels)]
                        key = (sample, labels)
                        assert value >= last.get(key, 0.0), \
                            f"counter {key} went backwards"
                        last[key] = value
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

        def stats_reader():
            try:
                for _ in range(self.SCRAPES):
                    st, raw, _ = _http(port, "GET", "/stats.json")
                    assert st == 200
                    doc = json.loads(raw)
                    serving = doc["serving"]
                    # torn-snapshot guard: the histogram summary's
                    # count can never exceed the queries that entered
                    hist_total = sum(
                        int(v) * int(k)
                        for k, v in serving["batchSizeHistogram"].items())
                    assert hist_total <= serving["batchedQueries"] \
                        + serving["deduped"] + serving["expired"] + 1
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

        workers = [threading.Thread(target=traffic) for _ in range(4)]
        readers = ([threading.Thread(target=scraper) for _ in range(2)]
                   + [threading.Thread(target=stats_reader)])
        for t in workers + readers:
            t.start()
        for t in readers:
            t.join(timeout=120)
        stop.set()
        for t in workers:
            t.join(timeout=30)
        assert not errors, errors[0]

    def test_event_server_scrapes_under_ingest(self, event_server):
        port = event_server.port
        stop = threading.Event()
        errors: list[BaseException] = []

        def traffic():
            while not stop.is_set():
                try:
                    st, _, _ = _http(
                        port, "POST", "/batch/events.json?accessKey=k",
                        [EVENT] * 5)
                    assert st == 200
                except Exception as e:   # noqa: BLE001
                    errors.append(e)
                    return

        def scraper():
            last = 0.0
            try:
                for _ in range(self.SCRAPES):
                    st, raw, _ = _http(port, "GET", "/metrics")
                    assert st == 200
                    families = parse_prometheus(raw.decode())
                    check_histogram_consistency(
                        families, "pio_ingest_batch_size")
                    check_histogram_consistency(
                        families, "pio_ingest_insert_seconds")
                    events = families["pio_ingest_events_total"]["samples"][
                        ("pio_ingest_events_total", ())]
                    assert events >= last, "events_total went backwards"
                    last = events
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

        workers = [threading.Thread(target=traffic) for _ in range(3)]
        readers = [threading.Thread(target=scraper) for _ in range(2)]
        for t in workers + readers:
            t.start()
        for t in readers:
            t.join(timeout=120)
        stop.set()
        for t in workers:
            t.join(timeout=30)
        assert not errors, errors[0]


# ---------------------------------------------------------------------------
# train stage breakdown + dashboard scrape + lint scope
# ---------------------------------------------------------------------------

def test_train_outcome_carries_stage_seconds(storage):
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.workflow.train import format_stage_times, run_train

    from tests.sample_engine import AlgoParams, DSParams

    params = EngineParams.of(
        data_source=DSParams(id=7, n_train=5),
        algorithms=[("sample", AlgoParams(id=0, mult=3))])
    outcome = run_train(
        engine_factory="tests.sample_engine.engine_factory",
        engine_params=params, variant={"id": "sample-engine"},
        storage=storage)
    assert outcome.status == "COMPLETED"
    assert set(outcome.stage_seconds) == {"read", "prepare", "train",
                                          "persist"}
    assert all(v >= 0 for v in outcome.stage_seconds.values())
    line = format_stage_times(outcome.stage_seconds)
    assert "read" in line and "persist" in line and "s" in line


def test_dashboard_metrics_scrape(storage):
    from predictionio_tpu.tools.dashboard import Dashboard

    dash = Dashboard(storage, ip="127.0.0.1", port=0)
    dash.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/metrics", timeout=10) as r:
            families = parse_prometheus(r.read().decode())
        assert ("pio_server_info",
                (("server", "dashboard"),
                 ("version", __import__("predictionio_tpu").__version__))
                ) in families["pio_server_info"]["samples"]
        check_histogram_consistency(families, "pio_http_request_seconds")
    finally:
        dash.stop()


def test_obs_is_in_lint_scope():
    """Satellite contract: the new subsystem is patrolled by the
    hot-path and resilience-bypass rules (analysis/config.py)."""
    from predictionio_tpu.analysis.config import HOT_PATHS, default_config

    assert "obs/" in HOT_PATHS
    policy = default_config()
    assert "obs/" in policy.rules["resilience-bypass"].paths
