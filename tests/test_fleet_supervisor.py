"""Self-healing fleet suite (docs/fleet.md "Supervision" /
"Autoscaling"): the process supervisor, the scale controller, and the
shared admin state across ``--workers`` siblings.

The acceptance scenarios:

- under live load through the router, ``kill -9`` one replica AND one
  worker sibling → the supervisor restores both within a bounded
  window, with ZERO 5xx from the replica death (the PR 6 guarantee
  preserved) and the restored worker folded back into the merged
  ``/metrics``;
- a crash-looping replica spec reaches the give-up latch WITHOUT
  hot-spinning (spawn count == threshold exactly), visible as
  ``pio_fleet_crash_loop 1``;
- scale controller e2e on ``ManualClock``: sustained pressure adds a
  replica that joins membership and serves traffic; sustained idle
  removes one only after the cooldown and DRAINS it via ``/readyz``
  before SIGTERM; dry-run changes nothing but exports
  ``pio_fleet_desired_replicas`` and decision counters;
- canary ``set_weight`` through one worker is observed by every
  sibling and survives a worker respawn (the admin spool).

Plus the satellite pins: the supervisor backoff schedule follows
RetryPolicy's full-jitter semantics on ``ManualClock``, drain-before-
kill ordering, the controller decision table (pressure/burn →
verdicts, cooldown and clamp edges), the membership probe-starvation
guard, jittered ``Retry-After`` hints, and the engine server's
``POST /drain`` latch.
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from predictionio_tpu.api.router_server import RouterServer
from predictionio_tpu.api.http_base import retry_after_header
from predictionio_tpu.fleet.canary import CanaryController
from predictionio_tpu.fleet.controller import (
    ScaleController,
    ScalePolicy,
    ScaleSignals,
    SupervisedFleetActuator,
    controller_collector,
    fleet_signals_reader,
)
from predictionio_tpu.fleet.membership import (
    Backend,
    BackendSpec,
    FleetMembership,
)
from predictionio_tpu.fleet.router import RouterConfig
from predictionio_tpu.fleet.stats import RouterStats, router_collector
from predictionio_tpu.fleet.supervisor import (
    WORKER,
    FleetSupervisor,
    SpawnSpec,
    SupervisorConfig,
    supervisor_collector,
)
from predictionio_tpu.fleet.transport import UpstreamResponse
from predictionio_tpu.obs.exporter import render_metrics
from predictionio_tpu.utils.resilience import ManualClock
from predictionio_tpu.workflow.deploy import ServerConfig

from tests.test_fleet_router import (
    EchoDeployed,
    echo_server,
    get_json,
    post_query,
    router_for,
)
from tests.test_observability import parse_prometheus

pytestmark = pytest.mark.fleet

HERE = os.path.dirname(os.path.abspath(__file__))
REPLICA_CHILD = os.path.join(HERE, "fleet_replica_child.py")
WORKER_CHILD = os.path.join(HERE, "fleet_worker_child.py")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

from tests.netutil import free_port, wait_until  # noqa: E402


def replica_spec(port: int, tag: str) -> SpawnSpec:
    return SpawnSpec(
        id=f"replica:{port}",
        spawn=lambda: subprocess.Popen(
            [sys.executable, REPLICA_CHILD,
             "--port", str(port), "--tag", tag]),
        address=f"127.0.0.1:{port}")


def direct_post(port: int, payload: dict, timeout: float = 5.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class FakeProcess:
    """Popen-shaped handle for deterministic supervisor units."""

    _pids = iter(range(40000, 50000))

    def __init__(self, stubborn: bool = False):
        self.pid = next(self._pids)
        self._code: int | None = None
        #: a stubborn child ignores SIGTERM (dies only on SIGKILL) —
        #: the kill-fallback path
        self.stubborn = stubborn
        self.calls: list[str] = []

    def poll(self):
        return self._code

    def die(self, code: int = 1) -> None:
        self._code = code

    def terminate(self) -> None:
        self.calls.append("terminate")
        if not self.stubborn:
            self._code = -15

    def kill(self) -> None:
        self.calls.append("kill")
        self._code = -9

    def wait(self, timeout=None):
        return self._code


# ---------------------------------------------------------------------------
# supervisor determinism on ManualClock (the satellite pin)
# ---------------------------------------------------------------------------

class TestSupervisorBackoffSchedule:
    def test_backoff_follows_retry_policy_full_jitter(self):
        """The respawn schedule IS RetryPolicy's: same seed, same
        draws, same delays — and a child is never respawned before its
        jittered delay elapses."""
        clock = ManualClock()
        cfg = SupervisorConfig(
            unhealthy_after=0, backoff_base_s=0.5, backoff_max_s=30.0,
            backoff_multiplier=2.0, crash_loop_threshold=10,
            crash_loop_window_s=1000.0)
        procs: list[FakeProcess] = []

        def spawn():
            p = FakeProcess()
            procs.append(p)
            return p

        sup = FleetSupervisor([SpawnSpec(id="r", spawn=spawn)], cfg,
                              clock=clock, rng=random.Random(7))
        sup.start(loop=False)
        assert len(procs) == 1
        expected_rng = random.Random(7)
        policy = cfg.backoff_policy()
        for i in range(4):
            procs[-1].die(1)
            sup.poll_once()                     # death -> backoff
            delay = policy.backoff(i, expected_rng)
            assert delay <= 30.0
            clock.advance(delay * 0.9)
            sup.poll_once()                     # not due yet
            assert len(procs) == i + 1, "respawned before its backoff"
            clock.advance(delay * 0.1 + 1e-9)
            sup.poll_once()                     # due now
            assert len(procs) == i + 2
        assert sup.snapshot()["respawns"] == 4

    def test_stability_resets_the_backoff_index(self):
        """A child that ran stably past the crash-loop window restarts
        from the BASE delay, not from wherever its death history left
        off (deaths age out of the window)."""
        clock = ManualClock()
        cfg = SupervisorConfig(
            unhealthy_after=0, backoff_base_s=1.0, backoff_max_s=64.0,
            backoff_multiplier=2.0, crash_loop_threshold=5,
            crash_loop_window_s=60.0)
        procs: list[FakeProcess] = []

        def spawn():
            p = FakeProcess()
            procs.append(p)
            return p

        sup = FleetSupervisor([SpawnSpec(id="r", spawn=spawn)], cfg,
                              clock=clock, rng=random.Random(11))
        sup.start(loop=False)
        for _ in range(3):                      # three quick deaths
            procs[-1].die(1)
            sup.poll_once()
            clock.advance(70.0)                 # past the window
            sup.poll_once()
        # all deaths aged out: the next death is index 0 again, whose
        # delay is bounded by the base cap (uniform(0, base))
        procs[-1].die(1)
        sup.poll_once()
        clock.advance(cfg.backoff_base_s + 1e-9)
        sup.poll_once()
        assert len(procs) == 5                  # respawned within base cap


class TestCrashLoopLatch:
    def test_latch_after_threshold_deaths_without_hot_spin(self):
        clock = ManualClock()
        cfg = SupervisorConfig(
            unhealthy_after=0, crash_loop_threshold=3,
            crash_loop_window_s=60.0, backoff_base_s=0.5,
            backoff_max_s=8.0)
        spawns: list[FakeProcess] = []

        def spawn():
            p = FakeProcess()
            p.die(13)                           # exits immediately
            spawns.append(p)
            return p

        sup = FleetSupervisor([SpawnSpec(id="bad", spawn=spawn)], cfg,
                              clock=clock, rng=random.Random(3))
        sup.start(loop=False)
        for _ in range(20):
            sup.poll_once()
            clock.advance(10.0)
        assert sup.crash_looped()
        # give-up means EXACTLY threshold spawn attempts, then silence
        assert len(spawns) == 3
        assert "give_up" in sup.child_events("bad")
        text = render_metrics(supervisor_collector(sup)())
        assert "pio_fleet_crash_loop 1" in text
        assert 'pio_fleet_child_up{child="bad",role="replica"} 0' in text
        for _ in range(5):                      # latched: stays quiet
            sup.poll_once()
            clock.advance(100.0)
        assert len(spawns) == 3

    def test_scale_up_refused_while_a_replica_is_crash_looped(self):
        """A latched child means the replica SPEC is broken — the
        actuator must refuse to spawn more of it, or the min-replica
        clamp would demand a fresh identically-broken spawn every
        cooldown forever (children and DOWN backends leaking)."""
        clock = ManualClock()

        def spawn():
            p = FakeProcess()
            p.die(1)
            return p

        sup = FleetSupervisor(
            [SpawnSpec(id="bad", spawn=spawn,
                       address="127.0.0.1:1")],
            SupervisorConfig(unhealthy_after=0, crash_loop_threshold=2,
                             crash_loop_window_s=60.0),
            clock=clock, rng=random.Random(5))
        sup.start(loop=False)
        for _ in range(6):
            sup.poll_once()
            clock.advance(5.0)
        assert sup.crash_looped()
        membership = FleetMembership([])
        actuator = SupervisedFleetActuator(
            sup, membership, make_spec=lambda i=None: replica_spec(
                free_port(), "never-spawned"))
        actuator.adopt("bad")
        assert actuator.current() == 0       # latched != capacity
        assert actuator.add_replica() is False
        assert sup.snapshot()["children"], "latched child retained"
        assert membership.backends == []     # nothing joined

    def test_give_up_hook_fires_once(self):
        clock = ManualClock()
        gave_up: list[str] = []

        def spawn():
            p = FakeProcess()
            p.die(1)
            return p

        sup = FleetSupervisor(
            [SpawnSpec(id="bad", spawn=spawn)],
            SupervisorConfig(unhealthy_after=0, crash_loop_threshold=2,
                             crash_loop_window_s=60.0),
            clock=clock, rng=random.Random(5),
            on_give_up=lambda spec: gave_up.append(spec.id))
        sup.start(loop=False)
        for _ in range(10):
            sup.poll_once()
            clock.advance(5.0)
        assert gave_up == ["bad"]


class _DrainRecorder:
    """Mini replica surface recording the drain conversation order."""

    def __init__(self):
        self.log: list[str] = []
        self.drained = False
        recorder = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _respond(self, status, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(length)
                if self.path == "/drain":
                    recorder.log.append("drain")
                    recorder.drained = True
                    self._respond(200, b'{"status": "draining"}')
                else:
                    self._respond(404, b"{}")

            def do_GET(self):
                if self.path == "/readyz":
                    recorder.log.append("readyz")
                    if recorder.drained:
                        self._respond(503, b'{"status": "draining"}')
                    else:
                        self._respond(200, b'{"status": "ready"}')
                else:
                    self._respond(200, b'{"status": "ok"}')

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestDrainBeforeKillOrdering:
    def _supervisor(self, spec, **cfg_overrides):
        cfg = SupervisorConfig(
            unhealthy_after=0, drain_poll_s=0.05, drain_settle_s=0.5,
            drain_timeout_s=2.0, term_grace_s=1.0, **cfg_overrides)
        return FleetSupervisor([spec], cfg, clock=ManualClock())

    def test_replica_removal_drains_then_terminates(self):
        recorder = _DrainRecorder()
        proc = FakeProcess()
        sup = self._supervisor(SpawnSpec(
            id="r", spawn=lambda: proc,
            address=f"127.0.0.1:{recorder.port}"))
        try:
            sup.start(loop=False)
            assert sup.remove("r") is True
            events = sup.child_events("r")
            assert events == ["spawn", "drain", "terminate"]
            # the replica heard the drain BEFORE any readiness poll,
            # and the process only got SIGTERM after both
            assert recorder.log[0] == "drain"
            assert "readyz" in recorder.log
            assert proc.calls == ["terminate"]
            assert proc.poll() is not None
        finally:
            recorder.close()

    def test_stubborn_child_gets_sigkill_after_grace(self):
        recorder = _DrainRecorder()
        proc = FakeProcess(stubborn=True)
        sup = self._supervisor(SpawnSpec(
            id="r", spawn=lambda: proc,
            address=f"127.0.0.1:{recorder.port}"))
        try:
            sup.start(loop=False)
            sup.remove("r")
            assert sup.child_events("r") == \
                ["spawn", "drain", "terminate", "kill"]
            assert proc.calls == ["terminate", "kill"]
        finally:
            recorder.close()

    def test_worker_removal_skips_the_drain(self):
        # workers share the public SO_REUSEPORT port: there is nothing
        # addressable to drain, SIGTERM is the whole protocol
        proc = FakeProcess()
        sup = self._supervisor(SpawnSpec(id="w", spawn=lambda: proc,
                                         role=WORKER))
        sup.start(loop=False)
        sup.remove("w")
        assert sup.child_events("w") == ["spawn", "terminate"]


# ---------------------------------------------------------------------------
# the controller decision table (ManualClock; the satellite pin)
# ---------------------------------------------------------------------------

class RecordingActuator:
    def __init__(self, current: int = 2):
        self.n = current
        self.calls: list[str] = []

    def current(self) -> int:
        return self.n

    def add_replica(self) -> bool:
        self.calls.append("up")
        self.n += 1
        return True

    def remove_replica(self) -> bool:
        self.calls.append("down")
        self.n -= 1
        return True


def make_controller(clock, actuator, signals, **policy_overrides):
    defaults = dict(min_replicas=1, max_replicas=3, pressure_up=0.5,
                    burn_up=14.4, pressure_down=0.1, up_sustain_s=10.0,
                    down_sustain_s=30.0, cooldown_s=20.0, interval_s=1.0,
                    dry_run=False)
    defaults.update(policy_overrides)
    return ScaleController(ScalePolicy(**defaults),
                           lambda: signals["v"], actuator, clock=clock)


class TestScaleControllerDecisionTable:
    def test_pressure_must_sustain_before_scale_up(self):
        clock = ManualClock()
        act = RecordingActuator(2)
        signals = {"v": ScaleSignals(pressure=0.9)}
        ctrl = make_controller(clock, act, signals)
        assert ctrl.tick() == "hold"            # hot, not sustained
        clock.advance(5.0)
        signals["v"] = ScaleSignals(pressure=0.3)   # neutral resets
        assert ctrl.tick() == "hold"
        clock.advance(20.0)
        signals["v"] = ScaleSignals(pressure=0.9)
        assert ctrl.tick() == "hold"            # sustain restarts
        clock.advance(10.0)
        assert ctrl.tick() == "up"
        assert act.calls == ["up"] and act.n == 3

    def test_fast_burn_triggers_scale_up_even_at_low_pressure(self):
        clock = ManualClock()
        act = RecordingActuator(1)
        signals = {"v": ScaleSignals(pressure=0.05, fast_burn=20.0)}
        ctrl = make_controller(clock, act, signals)
        assert ctrl.tick() == "hold"
        clock.advance(10.0)
        assert ctrl.tick() == "up"

    def test_cooldown_blocks_back_to_back_actions(self):
        clock = ManualClock()
        act = RecordingActuator(1)
        signals = {"v": ScaleSignals(pressure=0.9)}
        ctrl = make_controller(clock, act, signals, cooldown_s=25.0)
        ctrl.tick()
        clock.advance(10.0)
        assert ctrl.tick() == "up"              # first verdict (t=10)
        clock.advance(10.0)                     # hot again...
        assert ctrl.tick() == "hold"            # ...but sustain restarted
        clock.advance(10.0)                     # sustained again (t=30),
        assert ctrl.tick() == "cooldown_hold"   # 20s since action < 25s
        clock.advance(5.0)                      # cooldown served (t=35)
        assert ctrl.tick() == "up"
        assert act.n == 3

    def test_scale_down_needs_sustained_quiet_and_clamps_at_min(self):
        clock = ManualClock()
        act = RecordingActuator(2)
        signals = {"v": ScaleSignals(pressure=0.02)}
        ctrl = make_controller(clock, act, signals, cooldown_s=0.0)
        assert ctrl.tick() == "hold"            # quiet, not sustained
        clock.advance(30.0)
        assert ctrl.tick() == "down"
        assert act.n == 1
        clock.advance(0.1)
        assert ctrl.tick() == "hold"            # sustain restarted
        clock.advance(30.0)
        assert ctrl.tick() == "hold"            # clamped at min_replicas
        assert act.n == 1

    def test_burn_above_one_vetoes_scale_down(self):
        clock = ManualClock()
        act = RecordingActuator(2)
        signals = {"v": ScaleSignals(pressure=0.02, slow_burn=2.0)}
        ctrl = make_controller(clock, act, signals, cooldown_s=0.0)
        for _ in range(5):
            assert ctrl.tick() == "hold"        # quiet pressure, hot budget
            clock.advance(30.0)
        assert act.calls == []

    def test_clamps_at_max_replicas(self):
        clock = ManualClock()
        act = RecordingActuator(3)
        signals = {"v": ScaleSignals(pressure=0.9)}
        ctrl = make_controller(clock, act, signals, max_replicas=3,
                               cooldown_s=0.0)
        ctrl.tick()
        clock.advance(10.0)
        assert ctrl.tick() == "hold"            # desired clamps to current
        assert act.calls == []

    def test_unreadable_signals_hold_and_count(self):
        clock = ManualClock()
        act = RecordingActuator(2)

        def explode():
            raise ConnectionRefusedError("scrape down")

        ctrl = ScaleController(ScalePolicy(dry_run=False), explode, act,
                               clock=clock)
        assert ctrl.tick() == "error"
        assert ctrl.snapshot()["decisions"]["error"] == 1
        assert act.calls == []

    def test_dry_run_exports_but_never_actuates(self):
        clock = ManualClock()
        act = RecordingActuator(1)
        signals = {"v": ScaleSignals(pressure=0.9)}
        ctrl = make_controller(clock, act, signals, dry_run=True,
                               cooldown_s=0.0)
        ctrl.tick()
        clock.advance(10.0)
        assert ctrl.tick() == "up"
        assert act.calls == []                  # nothing actuated
        snap = ctrl.snapshot()
        assert snap["desiredReplicas"] == 2
        assert snap["actualReplicas"] == 1
        text = render_metrics(controller_collector(ctrl)())
        assert "pio_fleet_desired_replicas 2" in text
        assert "pio_fleet_actual_replicas 1" in text
        assert "pio_fleet_scale_dry_run 1" in text
        assert 'pio_fleet_scale_decisions_total{decision="up"} 1' in text


class TestFleetSignalsReader:
    def test_reader_parses_the_routers_own_fleet_metrics(self):
        server = echo_server("s0", batching=True, batch_max=4,
                             batch_wait_ms=1.0)
        router = router_for([server.port])
        try:
            for i in range(4):
                assert post_query(router.port, {"i": i})[0] == 200
            signals = fleet_signals_reader(router.service)()
            assert signals.pressure is None or 0.0 <= signals.pressure <= 1.0
            assert signals.fast_burn >= 0.0
            assert signals.slow_burn >= 0.0
        finally:
            router.stop()
            server.stop()


# ---------------------------------------------------------------------------
# probe-starvation guard (the satellite pin)
# ---------------------------------------------------------------------------

class _StubTransport:
    def __init__(self):
        self.mode = "timeout"

    def request(self, method, path, headers=None, body=None, *, timeout):
        if self.mode == "timeout":
            raise socket.timeout("probe starved under load")
        if self.mode == "refused":
            raise ConnectionRefusedError("nothing listening")
        return UpstreamResponse(200, b"{}", {})

    def close(self):
        pass


class TestProbeStarvationGuard:
    def _fixture(self):
        clock = ManualClock()
        backend = Backend(BackendSpec.parse("127.0.0.1:9", "stable"),
                          clock=clock)
        backend.transport = _StubTransport()
        membership = FleetMembership([backend], down_after=2,
                                     starvation_grace_s=10.0)
        return clock, backend, membership

    def test_timeout_with_healthy_data_path_never_marks_down(self):
        clock, backend, membership = self._fixture()
        backend.record_data_ok()
        for _ in range(6):
            membership._probe_and_record(backend)
        assert backend.state == "up"
        assert backend.probe_starved == 6
        # the counter reaches /metrics with backend labels
        metrics = router_collector(RouterStats(), membership,
                                   CanaryController())()
        starved = next(m for m in metrics
                       if m.name == "pio_router_probe_starved_total")
        assert starved.samples == [
            ({"backend": "127.0.0.1:9", "group": "stable"}, 6.0)]

    def test_guard_expires_with_the_data_path_proof(self):
        clock, backend, membership = self._fixture()
        backend.record_data_ok()
        clock.advance(11.0)                     # proof aged out
        membership._probe_and_record(backend)
        membership._probe_and_record(backend)
        assert backend.state == "down"          # down_after=2
        assert backend.probe_starved == 0

    def test_guard_requires_closed_breaker(self):
        clock, backend, membership = self._fixture()
        backend.record_data_ok()
        for _ in range(3):                      # default threshold=3
            backend.resilience.breaker.record_failure()
        assert backend.resilience.breaker.state == "open"
        membership._probe_and_record(backend)
        membership._probe_and_record(backend)
        assert backend.state == "down"

    def test_hard_failures_are_never_starvation(self):
        clock, backend, membership = self._fixture()
        backend.record_data_ok()
        backend.transport.mode = "refused"
        membership._probe_and_record(backend)
        membership._probe_and_record(backend)
        assert backend.state == "down"
        assert backend.probe_starved == 0


# ---------------------------------------------------------------------------
# jittered Retry-After + the engine drain latch (satellite pins)
# ---------------------------------------------------------------------------

class TestJitteredRetryAfter:
    def test_hints_jitter_within_25_pct_and_decorrelate(self):
        values = [float(retry_after_header(1.0)) for _ in range(50)]
        assert all(0.74 <= v <= 1.26 for v in values)
        assert len(set(values)) > 5             # not a constant

    def test_seeded_rng_is_reproducible_and_scales_with_the_hint(self):
        a = retry_after_header(4.0, random.Random(5))
        b = retry_after_header(4.0, random.Random(5))
        assert a == b
        assert 3.0 <= float(a) <= 5.0

    def test_router_shed_hint_is_jittered(self):
        slow = echo_server("slow", delay_s=0.4)
        router = router_for([slow.port], max_inflight=1)
        try:
            hints = []
            lock = threading.Lock()

            def client(i):
                status, _, headers = post_query(router.port, {"i": i})
                if status == 503:
                    with lock:
                        hints.append(headers.get("retry-after"))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
            assert hints, "nothing shed"
            assert all(0.74 <= float(h) <= 1.26 for h in hints)
        finally:
            router.stop()
            slow.stop()


class TestEngineDrainEndpoint:
    def test_drain_flips_readyz_until_undrain(self):
        from predictionio_tpu.api.engine_server import EngineService

        service = EngineService(EchoDeployed("d0"), config=ServerConfig())
        assert service.readyz()[0] == 200
        status, doc = service.handle("POST", "/drain", {}, {}, None)[:2]
        assert (status, doc["status"]) == (200, "draining")
        status, doc, headers = service.readyz()
        assert (status, doc["status"]) == (503, "draining")
        assert 0.74 <= float(headers["Retry-After"]) <= 1.26
        status, doc = service.handle(
            "POST", "/drain", {}, {}, {"action": "undrain"})[:2]
        assert (status, doc["status"]) == (200, "ready")
        assert service.readyz()[0] == 200

    def test_drain_requires_the_server_key(self):
        from predictionio_tpu.api.engine_server import EngineService

        service = EngineService(EchoDeployed("d1"),
                                config=ServerConfig(server_key="sek"))
        assert service.handle("POST", "/drain", {}, {}, None)[0] == 401
        assert service.handle("POST", "/drain",
                              {"accessKey": "sek"}, {}, None)[0] == 200


# ---------------------------------------------------------------------------
# THE chaos acceptance: kill -9 a replica AND a worker sibling
# ---------------------------------------------------------------------------

class TestChaosSelfHealing:
    def test_kill9_replica_and_worker_sibling_both_restored_zero_5xx(self):
        p1, p2 = free_port(), free_port()
        spool = tempfile.mkdtemp(prefix="pio-test-sup-")
        parent = RouterServer(RouterConfig(
            ip="127.0.0.1", port=0,
            backends=(f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"),
            reuse_port=True, worker_spool_dir=spool,
            probe_interval_s=0.25, admin_sync_interval_s=0.2))

        def worker_spawn():
            return subprocess.Popen(
                [sys.executable, WORKER_CHILD,
                 "--port", str(parent.port), "--spool", spool,
                 "--backend", f"127.0.0.1:{p1}",
                 "--backend", f"127.0.0.1:{p2}"])

        sup = FleetSupervisor(
            [replica_spec(p1, "r1"), replica_spec(p2, "r2"),
             SpawnSpec(id="worker:1", spawn=worker_spawn, role=WORKER)],
            SupervisorConfig(
                poll_interval_s=0.1, probe_timeout_s=1.0,
                unhealthy_after=0, backoff_base_s=0.2, backoff_max_s=1.0,
                crash_loop_threshold=5, crash_loop_window_s=30.0,
                drain_timeout_s=2.0, drain_settle_s=0.1,
                term_grace_s=3.0))
        sup.start()
        parent.start()
        try:
            # gate the load on the fleet being GENUINELY up: backends
            # start optimistically UP before the children even listen,
            # so /readyz alone passes during the boot race and the
            # first second of load would count boot-time 502s against
            # the replica-death guarantee
            def fleet_settled():
                for port in (p1, p2):
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/healthz",
                            timeout=2) as r:
                        if r.status != 200:
                            return False
                _, doc = get_json(parent.port, "/fleet")
                return all(b["state"] == "up" for b in doc["backends"])
            wait_until(fleet_settled, message="fleet settled")
            wait_until(lambda: sup.child_pid("worker:1") is not None,
                       message="worker sibling spawned")
            # the gate above samples ONE router per read, but
            # SO_REUSEPORT spreads connections across parent AND the
            # worker sibling — require a streak of successes over
            # fresh connections so BOTH routers' membership views have
            # finished their boot race before the counted load starts
            streak = 0
            deadline = time.time() + 15.0
            while streak < 10 and time.time() < deadline:
                status, _, _ = post_query(parent.port, {"warm": streak})
                streak = streak + 1 if status == 200 else 0
            assert streak >= 10, "fleet never settled across workers"

            statuses: list[tuple[int, dict]] = []
            transport_errors: list[str] = []
            lock = threading.Lock()
            stop_load = threading.Event()

            def client(cid: int) -> None:
                i = 0
                while not stop_load.is_set():
                    try:
                        status, body, _ = post_query(
                            parent.port, {"cid": cid, "i": i}, timeout=10)
                        with lock:
                            statuses.append((status, body))
                    except OSError as exc:
                        # a killed WORKER rips its live connections out
                        # from under clients — a transport error, not a
                        # served 5xx; the replica-death guarantee is
                        # about HTTP statuses
                        with lock:
                            transport_errors.append(repr(exc))
                    i += 1

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(4)]
            for t in threads:
                t.start()

            time.sleep(0.4)                       # load flowing
            replica_pid = sup.child_pid(f"replica:{p1}")
            os.kill(replica_pid, signal.SIGKILL)  # replica death
            time.sleep(1.5)                       # load over the corpse
            worker_pid = sup.child_pid("worker:1")
            os.kill(worker_pid, signal.SIGKILL)   # worker sibling death
            time.sleep(1.0)
            stop_load.set()
            for t in threads:
                t.join(timeout=20)

            assert len(statuses) > 50
            fives = [(s, b) for s, b in statuses if s >= 500]
            assert fives == [], (
                f"{len(fives)} 5xx of {len(statuses)}: {fives[:5]}")

            # the replica is restored: a NEW pid serving on the SAME
            # port, marked back up in membership
            wait_until(
                lambda: sup.child_pid(f"replica:{p1}") not in
                (None, replica_pid),
                message="replica respawned")
            wait_until(lambda: direct_post(p1, {"ping": 1})["tag"] == "r1",
                       message="restored replica serving")
            def replica_up():
                _, doc = get_json(parent.port, "/fleet")
                state = {b["id"]: b["state"] for b in doc["backends"]}
                return state[f"127.0.0.1:{p1}"] == "up"
            wait_until(replica_up, message="membership marked back up")

            # the worker sibling is restored and folded back into the
            # merged /metrics (spool reap + re-register)
            wait_until(
                lambda: sup.child_pid("worker:1") not in
                (None, worker_pid),
                message="worker respawned")

            def merged_workers_back():
                families = parse_prometheus(parent.service.metrics_text())
                return families["pio_router_workers"]["samples"][
                    ("pio_router_workers", ())] == 2.0
            wait_until(merged_workers_back,
                       message="restored worker in merged /metrics")

            assert sup.snapshot()["respawns"] >= 2
            assert not sup.crash_looped()
        finally:
            sup.shutdown()
            parent.stop()
            import shutil
            shutil.rmtree(spool, ignore_errors=True)

    def test_crash_looping_spec_latches_live_without_hot_spin(self):
        """A spec whose child exits immediately reaches the give-up
        latch (pio_fleet_crash_loop 1) after exactly `threshold` spawn
        attempts — damped by real backoff, never a spawn storm."""
        spawn_count = {"n": 0}

        def crashing_spawn():
            spawn_count["n"] += 1
            return subprocess.Popen(
                [sys.executable, "-c", "import sys; sys.exit(3)"])

        sup = FleetSupervisor(
            [SpawnSpec(id="crash", spawn=crashing_spawn)],
            SupervisorConfig(
                poll_interval_s=0.05, unhealthy_after=0,
                backoff_base_s=0.05, backoff_max_s=0.2,
                crash_loop_threshold=3, crash_loop_window_s=30.0))
        sup.start()
        try:
            wait_until(sup.crash_looped, timeout=10.0,
                       message="crash-loop latch")
            time.sleep(0.3)                     # latched: no more spawns
            assert spawn_count["n"] == 3
            text = render_metrics(supervisor_collector(sup)())
            assert "pio_fleet_crash_loop 1" in text
            doc = sup.snapshot()
            assert doc["crashLooped"] is True
            child = doc["children"][0]
            assert child["state"] == "crash_looped"
            assert child["lastExit"] == 3
        finally:
            sup.shutdown()


# ---------------------------------------------------------------------------
# scale controller e2e: real children, ManualClock decisions
# ---------------------------------------------------------------------------

class TestScaleControllerE2E:
    def test_scale_up_serves_then_scale_down_drains_via_readyz(self):
        clock = ManualClock()
        ports = [free_port(), free_port()]
        port_iter = iter(ports)

        def make_spec(_index=None):
            port = next(port_iter)
            return replica_spec(port, f"r{port}")

        sup = FleetSupervisor([], SupervisorConfig(
            unhealthy_after=0, drain_poll_s=0.05, drain_settle_s=0.1,
            drain_timeout_s=2.0, term_grace_s=5.0), clock=clock)
        spec1 = make_spec()
        sup.add(spec1)                           # the baseline replica
        router = router_for([ports[0]], probe_interval_s=0.2, up_after=1)
        actuator = SupervisedFleetActuator(
            sup, router.router.membership, make_spec)
        actuator.adopt(spec1.id)
        signals = {"v": ScaleSignals(pressure=0.9)}
        ctrl = make_controller(clock, actuator, signals, max_replicas=2,
                               up_sustain_s=10.0, down_sustain_s=30.0,
                               cooldown_s=0.0)
        try:
            wait_until(lambda: get_json(router.port, "/readyz")[0] == 200,
                       message="baseline replica routable")
            assert actuator.current() == 1

            # sustained pressure -> a replica is ADDED, joins
            # membership, and serves traffic
            assert ctrl.tick() == "hold"
            clock.advance(10.0)
            assert ctrl.tick() == "up"
            assert actuator.current() == 2
            new_id = f"127.0.0.1:{ports[1]}"
            assert new_id in [b.id
                              for b in router.router.membership.backends]

            tags = set()

            def both_tags_served():
                status, body, _ = post_query(router.port,
                                             {"q": len(tags)})
                assert status == 200
                tags.add(body["tag"])
                return len(tags) == 2
            wait_until(both_tags_served,
                       message="scaled-up replica serving traffic")

            # sustained idle -> removed ONLY after the cooldown, and
            # drained via /readyz before SIGTERM
            signals["v"] = ScaleSignals(pressure=0.0)
            assert ctrl.tick() == "hold"
            clock.advance(29.0)
            assert ctrl.tick() == "hold"         # cooldown not served yet
            clock.advance(1.0)
            assert ctrl.tick() == "down"
            events = sup.child_events(f"replica:{ports[1]}")
            assert "drain" in events and "terminate" in events
            assert events.index("drain") < events.index("terminate")
            assert new_id not in [
                b.id for b in router.router.membership.backends]
            assert actuator.current() == 1
            assert ctrl.snapshot()["desiredReplicas"] == 1

            def victim_gone():
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{ports[1]}/healthz", timeout=1)
                    return False
                except OSError:
                    return True
            wait_until(victim_gone, message="drained replica stopped")

            # the survivor still serves
            status, body, _ = post_query(router.port, {"after": 1})
            assert status == 200 and body["tag"] == f"r{ports[0]}"
        finally:
            ctrl.stop()
            sup.shutdown()
            router.stop()


# ---------------------------------------------------------------------------
# shared admin state across --workers siblings
# ---------------------------------------------------------------------------

def admin_post(port: int, payload: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/fleet/canary",
        data=json.dumps(payload).encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestSharedAdminState:
    def _worker_pair(self, backend_ports, canary_ports=(), spool=None,
                     **cfg):
        spool = spool or tempfile.mkdtemp(prefix="pio-test-admin-")

        def mk(port):
            return RouterServer(RouterConfig(
                ip="127.0.0.1", port=port,
                backends=tuple(f"127.0.0.1:{p}" for p in backend_ports),
                canary_backends=tuple(f"127.0.0.1:{p}"
                                      for p in canary_ports),
                reuse_port=True, worker_spool_dir=spool,
                probe_interval_s=0.25, admin_sync_interval_s=0.1,
                **cfg))

        w1 = mk(0)
        w2 = mk(w1.port)
        w1.start()
        w2.start()
        return w1, w2, spool

    def test_set_weight_reaches_all_siblings_and_survives_respawn(self):
        s0 = echo_server("s0")
        c0 = echo_server("c0")
        w1, w2, spool = self._worker_pair([s0.port], [c0.port])
        w3 = None
        try:
            status, doc = admin_post(w1.port, {"weight": 25})
            assert status == 200

            def both_adopted():
                return all(
                    w.service.router.canary.weight_pct == 25.0
                    for w in (w1, w2))
            wait_until(both_adopted, timeout=5.0,
                       message="both workers at weight 25")

            # a RESPAWNED worker adopts the shared state at startup
            # instead of booting with the launch-time weight (0)
            w3 = RouterServer(RouterConfig(
                ip="127.0.0.1", port=w1.port,
                backends=(f"127.0.0.1:{s0.port}",),
                canary_backends=(f"127.0.0.1:{c0.port}",),
                reuse_port=True, worker_spool_dir=spool,
                probe_interval_s=0.25, admin_sync_interval_s=0.1))
            w3.start()
            assert w3.service.router.canary.weight_pct == 25.0
        finally:
            for w in (w1, w2, w3):
                if w is not None:
                    w.stop()
            s0.stop()
            c0.stop()

    def test_guardrail_abort_is_published_to_the_spool(self):
        """The _exchange wiring end-to-end: a guardrail verdict tripped
        by REAL traffic publishes an abort document for the siblings."""
        stable = echo_server("s0")
        bad_canary = echo_server("c0", fail=True)
        spool = tempfile.mkdtemp(prefix="pio-test-abort-")
        router = RouterServer(RouterConfig(
            ip="127.0.0.1", port=0,
            backends=(f"127.0.0.1:{stable.port}",),
            canary_backends=(f"127.0.0.1:{bad_canary.port}",),
            canary_weight_pct=50.0, breaker_threshold=50,
            guardrail_min_requests=5, guardrail_max_error_rate=0.3,
            guardrail_window=20,
            worker_spool_dir=spool, probe_interval_s=0.25,
            admin_sync_interval_s=0.1))
        router.start()
        try:
            for i in range(60):
                status, _, _ = post_query(router.port, {"i": i})
                assert status == 200
                if router.router.canary.aborted:
                    break
            assert router.router.canary.aborted
            doc = router.service.worker_hub.read_admin()
            assert doc is not None
            assert doc["action"] == "abort"
            assert doc["seq"] >= 1
            assert "error rate" in doc["reason"]
        finally:
            router.stop()
            stable.stop()
            bad_canary.stop()
            import shutil
            shutil.rmtree(spool, ignore_errors=True)

    def test_abort_latches_every_sibling(self):
        """Both workers end aborted under a failing canary: whichever
        worker's guardrail trips first publishes, the other adopts —
        no sibling keeps routing canary traffic on a stale verdict."""
        stable = echo_server("s0")
        bad_canary = echo_server("c0", fail=True)
        w1, w2, spool = self._worker_pair(
            [stable.port], [bad_canary.port],
            canary_weight_pct=50.0, breaker_threshold=50,
            guardrail_min_requests=5, guardrail_max_error_rate=0.3,
            guardrail_window=20)
        try:
            for i in range(120):
                status, _, _ = post_query(w1.port, {"i": i})
                assert status == 200
                if all(w.service.router.canary.aborted for w in (w1, w2)):
                    break

            def both_aborted():
                return all(w.service.router.canary.aborted
                           for w in (w1, w2))
            wait_until(both_aborted, timeout=5.0,
                       message="abort latched on every sibling")
        finally:
            w1.stop()
            w2.stop()
            stable.stop()
            bad_canary.stop()
