"""Scenario test for examples/recommendation-filter-by-category
(reference: examples/scala-parallel-recommendation/filter-by-category):
item categories from $set events restrict recommendations pre-top-k."""

import os
import sys

import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.context import EngineContext
from predictionio_tpu.workflow.persistence import load_models
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples",
    "recommendation-filter-by-category",
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


@pytest.fixture
def storage_with_data(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, "FilterCategoryApp"))
    events = storage.get_events()
    events.init(app_id)
    # even items = "even" category, odd = "odd"; i0/i1 get both
    for i in range(12):
        cats = ["even" if i % 2 == 0 else "odd"]
        if i < 2:
            cats = ["even", "odd"]
        events.insert(
            Event(event="$set", entity_type="item", entity_id=f"i{i}",
                  properties=DataMap({"categories": cats})),
            app_id,
        )
    for u in range(16):
        for i in range(12):
            if i % 2 == u % 2:
                events.insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 5.0})),
                    app_id,
                )
    return storage


def test_category_filtered_recommendations(example_engine, storage_with_data):
    variant = {
        "id": "filter-by-category",
        "engineFactory": "engine.engine_factory",
        "datasource": {"params": {"app_name": "FilterCategoryApp"}},
        "algorithms": [
            {"name": "als",
             "params": {"rank": 8, "num_iterations": 8, "lambda_": 0.05,
                        "seed": 1, "use_mesh": False,
                        "exclude_seen": False}}
        ],
    }
    storage = storage_with_data
    outcome = run_train(variant=variant, storage=storage)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=storage)
    models = eng.prepare_deploy(
        ctx, ep, load_models(storage, outcome.instance_id))
    _, _, algos, serving = eng.make_components(ep)
    Query = example_engine.Query

    def ask(**kw):
        q = serving.supplement(Query(**kw))
        return serving.serve(
            q, [a.predict(m, q) for a, m in zip(algos, models)])

    # no categories: unrestricted
    free = ask(user="u0", num=6)
    assert len(free.item_scores) == 6

    # category restriction: only odd-category items (incl. the dual i0/i1)
    odd = ask(user="u0", num=6, categories=("odd",))
    items = [s.item for s in odd.item_scores]
    assert items and all(
        int(i[1:]) % 2 == 1 or i in ("i0", "i1") for i in items
    )

    # unknown category: empty-eligibility semantics -> nothing served
    none = ask(user="u0", num=6, categories=("nope",))
    assert none.item_scores == ()

    # the shipped engine.json binds as-is
    import json

    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        shipped = json.load(f)
    ep2 = eng.params_from_variant_json(shipped)
    assert ep2.algorithm_params_list[0][1].rank == 10
