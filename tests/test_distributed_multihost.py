"""Two-process jax.distributed smoke test over the PIO_* env contract.

The reference's cross-machine surface (spark-submit driver/executor
wiring, Runner.scala:185-307) is exercised by its integration suite;
ours is `jax.distributed.initialize` driven by PIO_NUM_HOSTS /
PIO_HOST_INDEX / PIO_COORDINATOR_ADDRESS (parallel/distributed.py).
This spawns a coordinator + worker process on this machine, each with
two virtual CPU devices, builds a 4-device global mesh spanning both,
and runs a cross-host reduction — the minimal proof the multi-host
path initializes and XLA collectives flow between processes.
"""

import os
import socket
import subprocess
import sys

import pytest

#: the container-artifact signature: some jaxlib CPU builds ship
#: without multiprocess collectives at all — every child fails with
#: this exact runtime error regardless of what the test computes.
#: Detected POST-HOC so a child failing for any OTHER reason still
#: fails the test (real regressions stay visible).
_CPU_NO_MULTIPROCESS = (
    "Multiprocess computations aren't implemented on the CPU backend")

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")
ALS_CHILD = os.path.join(os.path.dirname(__file__), "multihost_als_child.py")
FUSED_CHILD = os.path.join(os.path.dirname(__file__),
                           "multihost_fused_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(child: str) -> list[tuple[int, str, str]]:
    port = _free_port()
    env_base = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("PIO_", "XLA_", "JAX_"))
    }
    env_base["PYTHONPATH"] = REPO
    procs = []
    for idx in range(2):
        env = dict(
            env_base,
            PIO_NUM_HOSTS="2",
            PIO_HOST_INDEX=str(idx),
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for idx, (code, out, err) in enumerate(outs):
        if code != 0 and _CPU_NO_MULTIPROCESS in (out + err):
            pytest.skip(
                "container jaxlib CPU backend lacks multiprocess "
                "collectives (container artifact, not a regression): "
                f"{_CPU_NO_MULTIPROCESS!r}")
        assert code == 0, f"host {idx} failed:\n{out}\n{err}"
    return outs


def test_two_process_psum():
    outs = _run_children(CHILD)
    assert "RESULT host=0 total=6.0" in outs[0][1]
    assert "RESULT host=1 total=6.0" in outs[1][1]


def test_two_process_sharded_als_half_step():
    """A REAL ALS half-step program spanning two processes: each host
    stages its local slab shard (make_array_from_process_local_data —
    the only multi-process staging path), the jitted
    accumulate-then-solve program runs over the 4-device global mesh
    with XLA's cross-process collectives, and both hosts verify the
    replicated factors against a per-row NumPy oracle."""
    outs = _run_children(ALS_CHILD)
    assert "als_half_ok" in outs[0][1]
    assert "als_half_ok" in outs[1][1]
    # both hosts computed the identical replicated factor table
    n0 = outs[0][1].split("norm=")[1].strip()
    n1 = outs[1][1].split("norm=")[1].strip()
    assert n0 == n1


def test_two_process_fused_tp_training_run(
):
    """The FUSED (default) layout's full training scan across two
    processes on a dp×tp mesh (VERDICT r3 item 8): slabs shard over
    "data" (one process per data index), factor tables shard over
    "model" (shards span both processes), 2 full ALS iterations run as
    one device program with XLA's cross-process collectives, and both
    hosts verify the tables against a per-row NumPy f64 oracle."""
    outs = _run_children(FUSED_CHILD)
    assert "fused_tp_ok" in outs[0][1]
    assert "fused_tp_ok" in outs[1][1]
    n0 = outs[0][1].split("norm=")[1].strip()
    n1 = outs[1][1].split("norm=")[1].strip()
    assert n0 == n1


def test_single_host_noop(monkeypatch):
    """Without PIO_NUM_HOSTS>1 the initializer must stay inert (the
    single-host CLI path)."""
    from predictionio_tpu.parallel import distributed

    monkeypatch.delenv("PIO_NUM_HOSTS", raising=False)
    assert distributed.maybe_initialize_distributed() is False
