"""Quality parity: the device-path ALS must match the reference-math
NumPy ALS-WR (the MLlib `ALS.train` estimator) on MovieLens-class data.

The north-star gate (BASELINE.md) is throughput *at matching MAP@10*;
Spark/MLlib cannot run here (no JVM), so the anchor is an independent
NumPy implementation of the identical estimator — different data layout
(segment reductions vs padded slabs), different RNG stream — evaluated
under the reference's Evaluation.scala protocol (k-fold, Precision@K /
MAP@K with rating threshold, exclude-seen top-k). RMSE on held-out
ratings is the sharp check: same math + same hyperparameters must land
within seed-level noise. Ranking metrics are confirmed to sit inside
the reference implementation's own seed spread.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.data.movielens import (
    RatingsDataset,
    load_ratings_file,
    synthesize_ml100k,
)
from predictionio_tpu.e2 import quality

DATA = os.path.join(
    os.path.dirname(__file__), "..", "examples", "data", "sample_movielens.txt"
)


def small_ds(seed=3):
    """ML-100k-statistics reconstruction scaled down for CPU test speed."""
    return synthesize_ml100k(
        seed=seed, num_users=200, num_items=400, num_ratings=12_000
    )


class TestDataset:
    def test_generator_marginals(self):
        ds = synthesize_ml100k()
        assert (ds.num_users, ds.num_items, ds.nnz) == (943, 1682, 100_000)
        deg = np.bincount(ds.users, minlength=ds.num_users)
        assert deg.min() >= 20  # every ML-100k user has >=20 ratings
        assert 3.2 < ds.ratings.mean() < 3.8
        assert set(np.unique(ds.ratings)) <= {1.0, 2.0, 3.0, 4.0, 5.0}
        # deterministic across calls
        ds2 = synthesize_ml100k()
        assert np.array_equal(ds.items, ds2.items)
        assert np.array_equal(ds.ratings, ds2.ratings)
        # popularity skew: top 10% of items carry a large share
        item_deg = np.sort(np.bincount(ds.items, minlength=ds.num_items))[::-1]
        assert item_deg[: ds.num_items // 10].sum() > 0.3 * ds.nnz

    def test_vendored_sample_file(self):
        ds = load_ratings_file(DATA)
        # the Spark sample_movielens_data.txt shape
        assert ds.num_users == 30
        assert ds.num_items == 100
        assert ds.nnz == 1501
        assert ds.ratings.min() >= 1.0 and ds.ratings.max() <= 5.0

    def test_kfold_split_partitions(self):
        ds = small_ds()
        train, test = quality.kfold_split(ds, k_fold=5, fold=0)
        n_test = sum(len(v) for v in test.values())
        assert train.nnz + n_test == ds.nnz
        assert abs(n_test - ds.nnz / 5) < ds.nnz * 0.02


class TestParity:
    @pytest.fixture(scope="class")
    def result(self):
        return quality.compare_quality(
            small_ds(), rank=8, iterations=8, lam=0.05, k_fold=5
        )

    def test_rmse_matches_reference(self, result):
        """Sharp gate: same estimator => same held-out RMSE (seed noise
        on this config measured < 0.02)."""
        assert result["rmse_tpu"] == pytest.approx(result["rmse_ref"], abs=0.05)

    def test_rmse_beats_global_mean(self, result):
        """Both factorizations must explain real variance, i.e. beat the
        non-personalized global-mean predictor on the same split."""
        ds = small_ds()
        train, test = quality.kfold_split(ds, k_fold=5)
        mu = float(train.ratings.mean())
        vals = np.asarray(
            [r for lst in test.values() for _, r in lst], dtype=np.float64
        )
        baseline = float(np.sqrt(np.mean((vals - mu) ** 2)))
        assert result["rmse_tpu"] < baseline
        assert result["rmse_ref"] < baseline

    def test_map_within_reference_seed_spread(self, result):
        """MAP@10 of the device path must sit inside the band the
        reference implementation itself spans across seeds (explicit ALS
        is a weak top-N ranker — MLlib included — so the band is low and
        wide in relative terms; parity means landing in the same band,
        which we widen by its own width on each side)."""
        ds = small_ds()
        train, test = quality.kfold_split(ds, k_fold=5)
        maps = []
        for seed in (11, 12, 13):
            U, V = quality.numpy_als_wr(
                train, rank=8, iterations=8, lam=0.05, seed=seed
            )
            maps.append(
                quality.ranking_eval(
                    quality.factor_score_fn(U, V), train, test
                )["map@10"]
            )
        lo, hi = min(maps), max(maps)
        width = max(hi - lo, 1e-4)
        assert lo - width <= result["map10_tpu"] <= hi + width, (
            f"tpu MAP@10 {result['map10_tpu']} outside reference seed band "
            f"[{lo}, {hi}] ± {width}"
        )

    def test_factors_beat_popularity_on_learnable_signal(self):
        """On strongly-clustered preferences (the regime where top-N from
        explicit ALS is informative), the factor model must beat the
        popularity baseline — i.e. it learned personalization."""
        rng = np.random.default_rng(0)
        n_u, n_i, per = 120, 60, 24
        users, items, vals = [], [], []
        for u in range(n_u):
            liked = np.arange(u % 2, n_i, 2)
            pick = rng.choice(liked, size=per // 2, replace=False)
            other = rng.choice(
                np.arange((u + 1) % 2, n_i, 2), size=per // 2, replace=False
            )
            for i in pick:
                users.append(u), items.append(i), vals.append(5.0)
            for i in other:
                users.append(u), items.append(i), vals.append(1.0)
        ds = RatingsDataset(
            users=np.asarray(users, np.int32),
            items=np.asarray(items, np.int32),
            ratings=np.asarray(vals, np.float32),
            num_users=n_u,
            num_items=n_i,
        )
        train, test = quality.kfold_split(ds, k_fold=5)
        U, V = quality.numpy_als_wr(train, rank=8, iterations=10, lam=0.05)
        als = quality.ranking_eval(
            quality.factor_score_fn(U, V), train, test, threshold=4.0
        )
        pop = quality.ranking_eval(
            quality.popularity_score_fn(train), train, test, threshold=4.0
        )
        assert als["map@10"] > 2 * pop["map@10"]

    def test_implicit_beats_popularity_full_scale(self):
        """The bench gate (VERDICT r2 missing #1): on the full
        ML-100k-statistics dataset the implicit-feedback ALS ranking —
        the production ranking story, the ecommerce template's
        trainImplicit analogue — must beat the popularity baseline.
        Explicit ALS does not (and is not expected to: it models rating
        values, not interaction propensity)."""
        ds = synthesize_ml100k()
        train, test = quality.kfold_split(ds, k_fold=5)
        pop = quality.ranking_eval(
            quality.popularity_score_fn(train), train, test)
        imp = quality.implicit_ranking_eval(train, test)
        assert imp["map@10"] > pop["map@10"], (
            f"implicit {imp['map@10']:.4f} <= popularity {pop['map@10']:.4f}"
        )

    def test_implicit_beats_popularity_on_real_data(self):
        """The ranking win grounded OFF-generator (VERDICT r3 weak #1):
        on the vendored real Spark sample dataset — public data, no
        synthesis — implicit ALS must beat popularity on the mean over
        all 5 folds (round-4 measurement: 0.0989 vs 0.0435, and ahead
        on every individual fold; asserted on the mean because 30x100
        is small and per-fold margins are wide)."""
        r = quality.implicit_vs_popularity_kfold(load_ratings_file(DATA))
        assert r["map10_implicit"] > r["map10_popularity"], r


class TestRealSampleThroughFramework:
    """The vendored real dataset driven through the actual template
    components (event store -> DataSource -> Preparator -> ALSAlgorithm),
    mirroring the reference quickstart's data flow, with the framework's
    own MAP@10 metric agreeing with the harness metric."""

    def test_end_to_end_map_agreement(self, storage):
        from predictionio_tpu.core.datamap import DataMap
        from predictionio_tpu.core.event import Event
        from predictionio_tpu.storage.base import App
        from predictionio_tpu.templates import recommendation as rec
        from predictionio_tpu.workflow.context import EngineContext

        ds = load_ratings_file(DATA)
        app_id = storage.get_meta_data_apps().insert(App(0, "QualityApp"))
        events = storage.get_events()
        events.init(app_id)
        for u, i, r in zip(ds.user_ids(), ds.item_ids(), ds.ratings):
            events.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=str(u),
                    target_entity_type="item",
                    target_entity_id=str(i),
                    properties=DataMap({"rating": float(r)}),
                ),
                app_id,
            )

        ctx = EngineContext(storage=storage)
        source = rec.RecommendationDataSource(
            rec.DataSourceParams(app_name="QualityApp", eval_k=3)
        )
        folds = source.read_eval(ctx)
        td, _info, qa = folds[0]
        prep = rec.ALSPreparator()
        pd = prep.prepare(ctx, td)
        algo = rec.ALSAlgorithm(
            rec.ALSAlgorithmParams(rank=8, num_iterations=10, lambda_=0.05,
                                   use_mesh=False)
        )
        model = algo.train(ctx, pd)

        # framework metric over the fold's (query, actual) pairs
        metric = rec.MAPAtK(k=10)
        preds = algo.batch_predict(model, list(enumerate(q for q, _ in qa)))
        preds = [p for _, p in sorted(preds)]
        vals = [
            metric.calculate_qpa(q, p, a)
            for (q, a), p in zip(qa, preds)
        ]
        vals = [v for v in vals if v is not None]
        framework_map = float(np.mean(vals)) if vals else 0.0

        # harness metric from the model's raw factors on the same split
        train_ds = RatingsDataset(
            users=pd.coo.rows,
            items=pd.coo.cols,
            ratings=pd.coo.vals,
            num_users=pd.coo.num_rows,
            num_items=pd.coo.num_cols,
        )
        test_by_user = {}
        for q, actual in qa:
            if q.user not in pd.user_ids:
                continue
            u = pd.user_ids[q.user]
            test_by_user[int(u)] = [
                (int(pd.item_ids[i]), 5.0)
                for i in actual
                if i in pd.item_ids
            ]
        test_by_user = {u: v for u, v in test_by_user.items() if v}
        harness = quality.ranking_eval(
            quality.factor_score_fn(model.user_factors, model.item_factors),
            train_ds,
            test_by_user,
            threshold=0.0,
        )
        # protocols differ slightly (threshold semantics on actuals carry
        # no rating in read_eval: all held-out items count as relevant) —
        # the two computations must agree to rounding
        assert framework_map == pytest.approx(harness["map@10"], abs=0.02)
