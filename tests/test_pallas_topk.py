"""Fused pallas top-k kernel vs the XLA reference implementation.

Runs in interpret mode on the CPU test mesh; the same kernel compiles
on TPU (probed at dispatch, with transparent XLA fallback).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from predictionio_tpu.ops.pallas_topk import recommend_topk_fused, _kernel_mode
from predictionio_tpu.ops.topk import recommend_topk


def make_case(rng, b=8, items=700, rank=16, seen=12, k=10):
    user_vecs = jnp.asarray(rng.standard_normal((b, rank)), jnp.float32)
    item_f = jnp.asarray(rng.standard_normal((items, rank)), jnp.float32)
    seen_cols = jnp.asarray(rng.integers(0, items, (b, seen)), jnp.int32)
    seen_mask = jnp.asarray(rng.integers(0, 2, (b, seen)), jnp.float32)
    allow = jnp.asarray(rng.integers(0, 2, (items,)), jnp.float32)
    return user_vecs, item_f, seen_cols, seen_mask, allow, k


def test_kernel_runs_here():
    assert _kernel_mode() is not None


@pytest.mark.parametrize("items,k,tile", [
    (700, 10, 256),     # padded tail tile
    (512, 10, 512),     # single tile
    (1024, 20, 128),    # many tiles, larger k
    (130, 5, 128),      # items barely over one lane tile
])
def test_matches_xla_reference(items, k, tile):
    rng = np.random.default_rng(items + k)
    user_vecs, item_f, seen_cols, seen_mask, allow, _ = make_case(
        rng, items=items, k=k)
    ref_v, ref_i = recommend_topk(user_vecs, item_f, seen_cols, seen_mask,
                                  allow, k)
    got_v, got_i = recommend_topk_fused(user_vecs, item_f, seen_cols,
                                        seen_mask, allow, k, tile_i=tile,
                                        use_pallas=True)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    # values are continuous random floats -> argmax ties have measure zero
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))


def test_masks_are_respected():
    rng = np.random.default_rng(0)
    b, items, k = 4, 300, 8
    user_vecs, item_f, seen_cols, seen_mask, allow, _ = make_case(
        rng, b=b, items=items, k=k)
    vals, idx = recommend_topk_fused(user_vecs, item_f, seen_cols, seen_mask,
                                     allow, k, use_pallas=True)
    idx = np.asarray(idx)
    allow_np = np.asarray(allow)
    seen = {
        (r, int(c))
        for r in range(b)
        for c, m in zip(np.asarray(seen_cols)[r], np.asarray(seen_mask)[r])
        if m > 0
    }
    for r in range(b):
        for c in idx[r]:
            assert allow_np[c] > 0
            assert (r, int(c)) not in seen


def test_fewer_eligible_than_k_pads_with_neg_inf():
    rng = np.random.default_rng(1)
    user_vecs, item_f, seen_cols, seen_mask, _, _ = make_case(
        rng, b=2, items=256, k=10)
    allow = jnp.zeros((256,), jnp.float32).at[3].set(1).at[7].set(1)
    vals, idx = recommend_topk_fused(user_vecs, item_f, seen_cols,
                                     jnp.zeros_like(seen_mask), allow, 10,
                                     use_pallas=True)
    vals = np.asarray(vals)
    assert np.isfinite(vals[:, :2]).all()
    assert np.isneginf(vals[:, 2:]).all()
    assert set(np.asarray(idx)[:, :2].ravel()) <= {3, 7}
