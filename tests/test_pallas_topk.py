"""Fused pallas top-k kernel vs the XLA reference implementation.

Runs in interpret mode on the CPU test mesh; the same kernel compiles
on TPU (probed at dispatch, with transparent XLA fallback).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from predictionio_tpu.ops.pallas_topk import recommend_topk_fused, _kernel_mode
from predictionio_tpu.ops.topk import recommend_topk


def make_case(rng, b=8, items=700, rank=16, seen=12, k=10):
    user_vecs = jnp.asarray(rng.standard_normal((b, rank)), jnp.float32)
    item_f = jnp.asarray(rng.standard_normal((items, rank)), jnp.float32)
    seen_cols = jnp.asarray(rng.integers(0, items, (b, seen)), jnp.int32)
    seen_mask = jnp.asarray(rng.integers(0, 2, (b, seen)), jnp.float32)
    allow = jnp.asarray(rng.integers(0, 2, (items,)), jnp.float32)
    return user_vecs, item_f, seen_cols, seen_mask, allow, k


def test_kernel_runs_here():
    assert _kernel_mode() is not None


@pytest.mark.parametrize("items,k,tile", [
    (700, 10, 256),     # padded tail tile
    (512, 10, 512),     # single tile
    (1024, 20, 128),    # many tiles, larger k
    (130, 5, 128),      # items barely over one lane tile
])
def test_matches_xla_reference(items, k, tile):
    rng = np.random.default_rng(items + k)
    user_vecs, item_f, seen_cols, seen_mask, allow, _ = make_case(
        rng, items=items, k=k)
    ref_v, ref_i = recommend_topk(user_vecs, item_f, seen_cols, seen_mask,
                                  allow, k)
    got_v, got_i = recommend_topk_fused(user_vecs, item_f, seen_cols,
                                        seen_mask, allow, k, tile_i=tile,
                                        use_pallas=True)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    # values are continuous random floats -> argmax ties have measure zero
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))


def test_masks_are_respected():
    rng = np.random.default_rng(0)
    b, items, k = 4, 300, 8
    user_vecs, item_f, seen_cols, seen_mask, allow, _ = make_case(
        rng, b=b, items=items, k=k)
    vals, idx = recommend_topk_fused(user_vecs, item_f, seen_cols, seen_mask,
                                     allow, k, use_pallas=True)
    idx = np.asarray(idx)
    allow_np = np.asarray(allow)
    seen = {
        (r, int(c))
        for r in range(b)
        for c, m in zip(np.asarray(seen_cols)[r], np.asarray(seen_mask)[r])
        if m > 0
    }
    for r in range(b):
        for c in idx[r]:
            assert allow_np[c] > 0
            assert (r, int(c)) not in seen


def test_dispatch_contract():
    """Auto dispatch (use_pallas=None) always takes the XLA path (measured
    loser everywhere — ops/pallas_topk docstring); forced use outside the
    kernel's validity bounds is rejected instead of silently degrading."""
    import pytest as _pytest

    from predictionio_tpu.ops import pallas_topk as ptk

    rng = np.random.default_rng(0)
    uf = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
    itf = jnp.asarray(rng.standard_normal((64, 8)).astype(np.float32))
    cols = jnp.zeros((4, 8), jnp.int32)
    mask = jnp.zeros((4, 8), jnp.float32)
    allow = jnp.ones((64,), jnp.float32)

    # auto path == XLA result at any shape
    from predictionio_tpu.ops.topk import recommend_topk

    v1, i1 = ptk.recommend_topk_fused(uf, itf, cols, mask, allow, 5)
    v2, i2 = recommend_topk(uf, itf, cols, mask, allow, 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    # forced out-of-envelope -> explicit error, not a silent fallback
    with _pytest.raises(ValueError, match="envelope"):
        ptk.recommend_topk_fused(uf, itf, cols, mask, allow,
                                 ptk._MAX_K + 1, use_pallas=True)
    big = jnp.zeros((ptk._MAX_BATCH + 1, 8), jnp.float32)
    with _pytest.raises(ValueError, match="envelope"):
        ptk.recommend_topk_fused(
            big, itf, jnp.zeros((ptk._MAX_BATCH + 1, 8), jnp.int32),
            jnp.zeros((ptk._MAX_BATCH + 1, 8), jnp.float32), allow, 5,
            use_pallas=True)


def test_seen_trim_respects_unpacked_entries():
    """_trim_seen keeps a real entry sitting past the count-based width."""
    from predictionio_tpu.ops.pallas_topk import _trim_seen

    cols = jnp.zeros((2, 512), jnp.int32).at[1, 100].set(42)
    mask = jnp.zeros((2, 512), jnp.float32).at[1, 100].set(1.0)
    tcols, tmask = _trim_seen(cols, mask)
    assert tcols.shape[1] >= 101
    assert int(tcols[1, 100]) == 42 and float(tmask[1, 100]) == 1.0
    # fully-empty seen arrays trim to the smallest width
    tcols2, _ = _trim_seen(jnp.zeros((2, 512), jnp.int32),
                           jnp.zeros((2, 512), jnp.float32))
    assert tcols2.shape[1] == 8


def test_trimmed_seen_matches_reference():
    """End-to-end through recommend_topk_fused with a wide sparse pad."""
    rng = np.random.default_rng(7)
    user_vecs, item_f, _, _, allow, k = make_case(rng, b=4, items=700, k=10)
    cols = jnp.zeros((4, 512), jnp.int32).at[2, 60].set(5).at[0, 0].set(9)
    mask = jnp.zeros((4, 512), jnp.float32).at[2, 60].set(1.0).at[0, 0].set(1.0)
    ref_v, ref_i = recommend_topk(user_vecs, item_f, cols, mask, allow, k)
    got_v, got_i = recommend_topk_fused(user_vecs, item_f, cols, mask, allow,
                                        k, use_pallas=True)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))


def test_fewer_eligible_than_k_pads_with_neg_inf():
    rng = np.random.default_rng(1)
    user_vecs, item_f, seen_cols, seen_mask, _, _ = make_case(
        rng, b=2, items=256, k=10)
    allow = jnp.zeros((256,), jnp.float32).at[3].set(1).at[7].set(1)
    vals, idx = recommend_topk_fused(user_vecs, item_f, seen_cols,
                                     jnp.zeros_like(seen_mask), allow, 10,
                                     use_pallas=True)
    vals = np.asarray(vals)
    assert np.isfinite(vals[:, :2]).all()
    assert np.isneginf(vals[:, 2:]).all()
    assert set(np.asarray(idx)[:, :2].ravel()) <= {3, 7}


def test_chunked_topk_matches_flat():
    """recommend_topk_chunked: identical results to the flat path with
    seen masks, allow vectors, and non-divisible catalog sizes."""
    from predictionio_tpu.ops.topk import recommend_topk, recommend_topk_chunked

    rng = np.random.default_rng(11)
    B, I, K, S, k = 6, 1000, 8, 16, 7   # I not a multiple of the chunk
    uf = jnp.asarray(rng.standard_normal((B, K)).astype(np.float32))
    itf = jnp.asarray(rng.standard_normal((I, K)).astype(np.float32))
    cols = np.zeros((B, S), np.int32)
    mask = np.zeros((B, S), np.float32)
    for b in range(B):
        seen = rng.choice(I, size=5, replace=False)
        cols[b, :5] = seen
        mask[b, :5] = 1.0
    allow = np.ones((I,), np.float32)
    allow[rng.choice(I, size=50, replace=False)] = 0.0

    v1, i1 = recommend_topk(uf, itf, jnp.asarray(cols), jnp.asarray(mask),
                            jnp.asarray(allow), k)
    v2, i2 = recommend_topk_chunked(uf, itf, jnp.asarray(cols),
                                    jnp.asarray(mask), jnp.asarray(allow), k,
                                    chunk=256)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-5)
    # no seen or disallowed item leaks through
    for b in range(B):
        got = set(np.asarray(i2)[b].tolist())
        assert not (got & set(cols[b][:5].tolist()))
        assert all(allow[i] > 0 for i in got)


def test_fused_auto_uses_chunked_at_scale():
    """The auto path dispatches to the chunked formulation at catalog
    scale and stays equal to the flat path."""
    from predictionio_tpu.ops import pallas_topk as ptk
    from predictionio_tpu.ops.topk import recommend_topk

    rng = np.random.default_rng(12)
    B, K, k = max(ptk._MIN_BATCH, 4), 8, 5
    I = ptk._MIN_ITEMS
    uf = jnp.asarray(rng.standard_normal((B, K)).astype(np.float32))
    itf = jnp.asarray(rng.standard_normal((I, K)).astype(np.float32))
    cols = jnp.zeros((B, 8), jnp.int32)
    mask = jnp.zeros((B, 8), jnp.float32)
    allow = jnp.ones((I,), jnp.float32)
    import predictionio_tpu.ops.topk as topk_mod

    calls = []
    orig = topk_mod.recommend_topk_chunked
    topk_mod.recommend_topk_chunked = (
        lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1])
    try:
        v1, i1 = ptk.recommend_topk_fused(uf, itf, cols, mask, allow, k)
    finally:
        topk_mod.recommend_topk_chunked = orig
    assert calls, "auto path should take the chunked formulation at scale"
    v2, i2 = recommend_topk(uf, itf, cols, mask, allow, k)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_chunked_underfilled_slots_never_collide():
    """Fewer eligible items than k: non-finite slots carry out-of-range
    sentinels so no index ever duplicates a real pick (the flat path
    guarantees distinctness via full-width top_k)."""
    from predictionio_tpu.ops.topk import recommend_topk, recommend_topk_chunked

    B, I, K, k = 2, 600, 4, 5
    rng = np.random.default_rng(0)
    uf = jnp.asarray(rng.standard_normal((B, K)).astype(np.float32))
    itf = jnp.asarray(rng.standard_normal((I, K)).astype(np.float32))
    cols = jnp.zeros((B, 8), jnp.int32)
    mask = jnp.zeros((B, 8), jnp.float32)
    allow = np.zeros((I,), np.float32)
    allow[0] = allow[1] = 1.0              # only 2 eligible, both ix < k
    v1, i1 = recommend_topk(uf, itf, cols, mask, allow, k)
    v2, i2 = recommend_topk_chunked(uf, itf, cols, mask,
                                    jnp.asarray(allow), k, chunk=256)
    # finite slots agree with the flat path
    fin = np.isfinite(np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1)[fin], np.asarray(i2)[fin])
    # every row's indices are distinct (no real pick duplicated)
    for b in range(B):
        row = np.asarray(i2)[b]
        assert len(set(row.tolist())) == k
        assert all(ix >= I for ix in row[~fin[b]])

    # fully-masked case: all sentinels, all -inf
    v3, i3 = recommend_topk_chunked(uf, itf, cols, mask,
                                    jnp.zeros((I,), jnp.float32), k, chunk=256)
    assert not np.isfinite(np.asarray(v3)).any()
    assert (np.asarray(i3) >= I).all()
