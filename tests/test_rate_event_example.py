"""Scenario test for examples/ecommerce-train-with-rate-event — the
reference's train-with-rate-event ecommerce variant: rate events with a
rating property feed implicit ALS as confidence weights, latest rating
per (user, item) wins."""

import json
import os
import sys
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.context import EngineContext
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples",
    "ecommerce-train-with-rate-event",
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


@pytest.fixture
def seeded_storage(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, "RateEcommApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(17)
    t0 = datetime.now(timezone.utc)
    for u in range(20):
        for i in range(16):
            if rng.random() < 0.5:
                same = (i % 2) == (u % 2)
                rating = float(
                    rng.integers(4, 6) if same else rng.integers(1, 3))
                events.insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap({"rating": rating}),
                          event_time=t0),
                    app_id,
                )
    # u0 rates i1 low at t0+1, then re-rates 5.0 at t0+5: latest wins
    for minutes, rating in ((1, 1.0), (5, 5.0)):
        events.insert(
            Event(event="rate", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": rating}),
                  event_time=t0 + timedelta(minutes=minutes)),
            app_id,
        )
    return storage


def test_latest_rating_wins_and_weights_are_ratings(
        example_engine, seeded_storage):
    ds = example_engine.RateEventDataSource(
        example_engine.RateDataSourceParams(app_name="RateEcommApp"))
    td = ds.read_training(EngineContext(storage=seeded_storage))
    by_pair = {(u, i): w
               for u, i, w in zip(td.users, td.items, td.weights)}
    assert by_pair[("u0", "i1")] == 5.0           # the re-rate superseded
    assert set(np.unique(td.weights)) <= {1.0, 2.0, 4.0, 5.0}
    assert len(td.users) == len(set(zip(td.users, td.items)))  # deduped


def test_trains_and_high_ratings_drive_recommendations(
        example_engine, seeded_storage):
    from predictionio_tpu.templates.ecommerce import Query
    from predictionio_tpu.workflow.persistence import load_models

    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        variant = json.load(f)
    variant["algorithms"][0]["params"]["use_mesh"] = False
    outcome = run_train(variant=variant, storage=seeded_storage)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=seeded_storage)
    _, _, algos, _ = eng.make_components(ep)
    models = eng.prepare_deploy(
        ctx, ep, load_models(seeded_storage, outcome.instance_id),
        algorithms=algos)

    # high-rated (same-cluster) items dominate a cluster user's top-k
    pred = algos[0].predict(models[0], Query(user="u2", num=4))
    recs = [s.item for s in pred.item_scores]
    assert recs
    even = sum(1 for i in recs if int(i[1:]) % 2 == 0)
    assert even >= len(recs) - 1, recs

    # unknown-user fallback must work on a rate-only app: the engine
    # json routes similarEvents at "rate" (the template default "view"
    # would silently return nothing here). No hand-wired context: the
    # deploy wiring's load_model already stashed it on the serving
    # instance.
    seeded_storage.get_events().insert(
        Event(event="rate", entity_type="user", entity_id="ghost",
              target_entity_type="item", target_entity_id="i2",
              properties=DataMap({"rating": 5.0})),
        seeded_storage.get_meta_data_apps().get_by_name("RateEcommApp").id)
    ghost = algos[0].predict(models[0], Query(user="ghost", num=4))
    assert ghost.item_scores, "unknown-user fallback returned nothing"
