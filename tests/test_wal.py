"""Unit tests for the write-ahead event journal (data/wal.py): framing,
CRC handling, torn-tail recovery, rotation under concurrent append,
drainer semantics (batch runs, per-record isolation, dead-letter
quarantine), disk-budget backpressure, and the drain-aware Retry-After
hint. The live-server chaos pins are in tests/test_wal_durability.py."""

import json
import os
import threading
import uuid
import zlib

import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.data.wal import (
    _HEADER,
    BLOCKED,
    EMPTY,
    PROGRESS,
    UNAVAILABLE,
    WalDrainer,
    WalFullError,
    WriteAheadLog,
    decode_record,
    encode_record,
    scan_status,
)
from predictionio_tpu.utils.resilience import StorageUnavailableError

pytestmark = pytest.mark.wal


def make_event(i: int, app_suffix: str = "") -> Event:
    return Event(
        event="rate", entity_type="user", entity_id=f"u{i}{app_suffix}",
        target_entity_type="item", target_entity_id=f"i{i}",
        properties=DataMap({"rating": i % 5}),
    ).with_event_id(uuid.uuid4().hex)


def fill(wal: WriteAheadLog, n: int, app_id: int = 1,
         channel_id=None) -> list[Event]:
    events = [make_event(i) for i in range(n)]
    for e in events:
        wal.append(encode_record(e, app_id, channel_id))
    return events


class Sink:
    """An insert_batch spy with scriptable failures."""

    def __init__(self):
        self.inserted: list[tuple[Event, int, object]] = []
        self.fail = None          # exception to raise, or callable(event)
        self.calls = 0

    def insert_batch(self, events, app_id, channel_id=None):
        self.calls += 1
        if self.fail is not None:
            exc = self.fail(events) if callable(self.fail) else self.fail
            if exc is not None:
                raise exc
        self.inserted.extend((e, app_id, channel_id) for e in events)
        return [e.event_id for e in events]


# ---------------------------------------------------------------------------
# framing / recovery
# ---------------------------------------------------------------------------

class TestFraming:
    def test_round_trip(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        events = fill(wal, 5, app_id=7, channel_id=3)
        entries = wal.read_pending()
        assert len(entries) == 5
        for entry, original in zip(entries, events):
            event, app_id, channel_id = decode_record(entry.payload)
            assert app_id == 7 and channel_id == 3
            assert event.event_id == original.event_id
            assert event.event_time == original.event_time
            assert event.properties.to_json() == original.properties.to_json()

    def test_pre_assigned_id_required(self, tmp_path):
        with pytest.raises(ValueError, match="pre-assigned"):
            encode_record(Event(event="e", entity_type="t",
                                entity_id="x"), 1, None)

    def test_crc_corrupt_record_skipped_and_counted(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        events = fill(wal, 3)
        wal.close()
        # flip one payload byte of the MIDDLE record on disk
        path = os.path.join(d, "wal-00000001.seg")
        entries_before = []
        data = bytearray(open(path, "rb").read())
        off = 0
        while off + _HEADER.size <= len(data):
            length, _ = _HEADER.unpack_from(data, off)
            entries_before.append(off)
            off += _HEADER.size + length
        victim = entries_before[1] + _HEADER.size  # first payload byte
        data[victim] ^= 0xFF
        with open(path, "wb") as f:
            f.write(data)

        wal2 = WriteAheadLog(d)
        assert wal2.corrupt_records == 1
        assert wal2.pending_records() == 2
        entries = wal2.read_pending()
        replayed = [decode_record(e.payload)[0].event_id for e in entries]
        assert replayed == [events[0].event_id, events[2].event_id]

    def test_torn_tail_truncated_on_reopen(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        fill(wal, 4)
        wal.close()
        path = os.path.join(d, "wal-00000001.seg")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)  # mid-frame: kill -9 artifact
        wal2 = WriteAheadLog(d)
        assert wal2.torn_bytes_truncated > 0
        assert wal2.pending_records() == 3
        # the file itself was truncated back to a whole-frame boundary
        assert os.path.getsize(path) < size - 7
        # and appends continue cleanly after the truncate point
        extra = make_event(99)
        wal2.append(encode_record(extra, 1, None))
        ids = [decode_record(e.payload)[0].event_id
               for e in wal2.read_pending()]
        assert ids[-1] == extra.event_id and len(ids) == 4

    def test_insane_length_header_treated_as_torn(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        fill(wal, 2)
        wal.close()
        path = os.path.join(d, "wal-00000001.seg")
        with open(path, "ab") as f:
            f.write(_HEADER.pack(1 << 30, 0) + b"garbage")
        wal2 = WriteAheadLog(d)
        assert wal2.pending_records() == 2
        assert wal2.torn_bytes_truncated > 0

    def test_scan_status_does_not_mutate(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        fill(wal, 3)
        wal.close()
        path = os.path.join(d, "wal-00000001.seg")
        with open(path, "ab") as f:
            f.write(b"\x01\x02")  # torn tail
        size = os.path.getsize(path)
        doc = scan_status(d)
        assert doc["depth"] == 3 and doc["tornTail"] is True
        assert os.path.getsize(path) == size  # untouched


# ---------------------------------------------------------------------------
# rotation
# ---------------------------------------------------------------------------

class TestRotation:
    def test_rotation_under_concurrent_append(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d, segment_max_bytes=512)
        n_threads, per_thread = 8, 25
        ids = [[make_event(t * 1000 + i) for i in range(per_thread)]
               for t in range(n_threads)]
        errors = []

        def writer(t):
            try:
                for e in ids[t]:
                    wal.append(encode_record(e, 1, None))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        total = n_threads * per_thread
        assert wal.pending_records() == total
        # many segments, no record lost or torn across any boundary
        assert wal.stats()["segments"] > 3
        entries = wal.read_pending(max_records=total)
        got = {decode_record(e.payload)[0].event_id for e in entries}
        want = {e.event_id for group in ids for e in group}
        assert got == want
        # reopen sees the identical pending set (recovery counts match)
        wal.close()
        wal2 = WriteAheadLog(d)
        assert wal2.pending_records() == total

    def test_consumed_segments_reaped(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d, segment_max_bytes=256)
        fill(wal, 20)
        assert wal.stats()["segments"] > 2
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == PROGRESS
        assert wal.pending_records() == 0
        # only the active segment remains
        assert wal.stats()["segments"] == 1


# ---------------------------------------------------------------------------
# drainer semantics
# ---------------------------------------------------------------------------

class TestDrainer:
    def test_batches_by_consecutive_app_channel_runs(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        e1, e2 = make_event(1), make_event(2)
        e3 = make_event(3)
        wal.append(encode_record(e1, 1, None))
        wal.append(encode_record(e2, 1, None))
        wal.append(encode_record(e3, 2, 5))
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == PROGRESS
        # 2 runs -> 2 insert_batch calls, routing preserved
        assert sink.calls == 2
        assert [(a, c) for _, a, c in sink.inserted] == [
            (1, None), (1, None), (2, 5)]

    def test_unavailable_backs_off_and_preserves_order(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        events = fill(wal, 4)
        sink = Sink()
        sink.fail = StorageUnavailableError("dead", "down")
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == UNAVAILABLE
        assert wal.pending_records() == 4
        sink.fail = None
        assert drainer.drain_once() == PROGRESS
        assert [e.event_id for e, _, _ in sink.inserted] == [
            e.event_id for e in events]

    def test_poison_record_quarantined_after_n_attempts(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        good1, bad, good2 = make_event(1), make_event(2), make_event(3)
        for e in (good1, bad, good2):
            wal.append(encode_record(e, 1, None))
        sink = Sink()

        def fail_bad(events):
            if any(e.event_id == bad.event_id for e in events):
                return RuntimeError("constraint violation")
            return None

        sink.fail = fail_bad
        drainer = WalDrainer(wal, sink.insert_batch, max_replay_attempts=3)
        # pass 1: batch fails -> per-record: good1 lands, bad attempt 1
        assert drainer.drain_once() == BLOCKED
        assert [e.event_id for e, _, _ in sink.inserted] == [good1.event_id]
        assert wal.pending_records() == 2
        # passes 2..3: bad escalates to quarantine, good2 drains
        assert drainer.drain_once() == BLOCKED
        assert drainer.drain_once() == PROGRESS
        assert wal.pending_records() == 0
        assert [e.event_id for e, _, _ in sink.inserted] == [
            good1.event_id, good2.event_id]
        dead = list(wal.dead_letters())
        assert len(dead) == 1
        assert dead[0]["attempts"] == 3
        assert "constraint violation" in dead[0]["reason"]
        assert dead[0]["record"]["e"]["eventId"] == bad.event_id

    def test_undecodable_record_quarantined_in_order(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        before = make_event(1)
        wal.append(encode_record(before, 1, None))
        wal.append(b"{not json")          # poison payload, valid CRC
        after = make_event(2)
        wal.append(encode_record(after, 1, None))
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == PROGRESS
        assert wal.pending_records() == 0
        assert [e.event_id for e, _, _ in sink.inserted] == [
            before.event_id, after.event_id]
        dead = list(wal.dead_letters())
        assert len(dead) == 1 and "undecodable" in dead[0]["reason"]

    def test_requeue_dead_letters(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        events = fill(wal, 2)
        sink = Sink()
        sink.fail = RuntimeError("always")
        drainer = WalDrainer(wal, sink.insert_batch, max_replay_attempts=1)
        while wal.pending_records():
            drainer.drain_once()
        assert wal.stats()["deadLetterTotal"] == 2
        assert wal.requeue_dead_letters() == (2, 0)
        assert wal.pending_records() == 2
        assert list(wal.dead_letters()) == []
        sink.fail = None
        assert drainer.drain_once() == PROGRESS
        assert {e.event_id for e, _, _ in sink.inserted} == {
            e.event_id for e in events}

    def test_requeue_preserves_undecodable_envelopes(self, tmp_path):
        """--requeue must never destroy evidence: an envelope whose
        record cannot be re-journaled (quarantined-as-undecodable)
        stays in the dead-letter series instead of being reaped with
        the segments."""
        wal = WriteAheadLog(str(tmp_path / "wal"))
        ok = make_event(1)
        wal.append(encode_record(ok, 1, None))
        wal.append(b"\x00garbage payload")   # valid CRC, undecodable
        sink = Sink()
        sink.fail = RuntimeError("always")
        drainer = WalDrainer(wal, sink.insert_batch, max_replay_attempts=1)
        while wal.pending_records():
            drainer.drain_once()
        assert wal.stats()["deadLetterTotal"] == 2
        sink.fail = None
        assert wal.requeue_dead_letters() == (1, 1)
        # the decodable record is live again; the undecodable envelope
        # survives for inspection
        assert wal.pending_records() == 1
        remaining = list(wal.dead_letters())
        assert len(remaining) == 1
        assert "undecodable" in remaining[0]["record"]

    def test_replay_survives_restart_idempotently(self, tmp_path):
        """Crash between insert and cursor commit replays the same
        record again — upsert semantics make that invisible."""
        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        events = fill(wal, 3)
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == PROGRESS
        wal.close()
        # simulate the crash: restore the PRE-drain cursor
        with open(os.path.join(d, "wal.cursor"), "w") as f:
            json.dump({"segment": 1, "offset": 0, "replayedTotal": 0,
                       "deadLetterTotal": 0}, f)
        # the reaped-segment case is separate; here the segment remains
        wal2 = WriteAheadLog(d)
        assert wal2.pending_records() == 3
        drainer2 = WalDrainer(wal2, sink.insert_batch)
        assert drainer2.drain_once() == PROGRESS
        # re-inserted under the SAME pre-assigned ids
        assert [e.event_id for e, _, _ in sink.inserted] == [
            e.event_id for e in events] * 2


# ---------------------------------------------------------------------------
# disk budget / backpressure
# ---------------------------------------------------------------------------

class TestBudget:
    def test_budget_flip_and_recovery(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"), max_bytes=1500)
        appended = 0
        with pytest.raises(WalFullError):
            for i in range(1000):
                wal.append(encode_record(make_event(i), 1, None))
                appended += 1
        assert 0 < appended < 1000
        assert wal.is_full()
        # draining frees budget: appends succeed again
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.drain_once() == PROGRESS
        assert not wal.is_full()
        wal.append(encode_record(make_event(5000), 1, None))
        assert wal.pending_records() == 1

    def test_backpressure_hint_shrinks_with_depth(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"))
        fill(wal, 100)
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch, batch_max=10)
        assert drainer.backpressure_hint() is None  # no rate observed yet
        drainer.drain_once()
        drainer.drain_once()
        rate = drainer.drain_rate()
        assert rate is not None and rate > 0
        hint_deep = drainer.backpressure_hint()
        # drain more: at a comparable rate the hint must shrink with
        # depth (pin the formula's monotonicity, not the wall clock)
        with drainer._lock:
            drainer._rate_ewma = rate
        depth_before = wal.pending_records()
        while wal.pending_records() > depth_before // 4:
            drainer.drain_once()
        with drainer._lock:
            drainer._rate_ewma = rate
        hint_shallow = drainer.backpressure_hint()
        assert hint_shallow is not None and hint_deep is not None
        assert hint_shallow <= hint_deep

    def test_mode_gauge_values(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "wal"), max_bytes=1200)
        sink = Sink()
        drainer = WalDrainer(wal, sink.insert_batch)
        assert drainer.mode() == 0                   # idle
        appended = 0
        try:
            for i in range(100):
                wal.append(encode_record(make_event(i), 1, None))
                appended += 1
        except WalFullError:
            pass
        assert drainer.mode() == 2                   # backpressure
        drainer.drain_once()
        assert wal.pending_records() == 0
        assert drainer.mode() == 0

    def test_pio_wal_cli_round_trip(self, tmp_path, capsys, monkeypatch):
        """`pio wal status` (non-mutating) -> `replay` (drains into the
        configured storage) -> `dead-letter` (empty) — the operator
        surface over a real journal directory."""
        from predictionio_tpu.cli.pio import main

        d = str(tmp_path / "wal")
        wal = WriteAheadLog(d)
        events = fill(wal, 4)
        wal.close()
        for var, val in {
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }.items():
            monkeypatch.setenv(var, val)
        assert main(["wal", "status", "--wal-dir", d, "--format",
                     "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["depth"] == 4 and doc["tornTail"] is False
        assert main(["wal", "replay", "--wal-dir", d]) == 0
        assert "replay complete" in capsys.readouterr().out
        # drained into the env-configured store is proven by depth 0 +
        # replayedTotal (the CLI builds its own Storage; the memory
        # backend is per-process so contents are checked in the
        # event-server suites)
        assert main(["wal", "status", "--wal-dir", d, "--format",
                     "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["depth"] == 0 and doc["replayedTotal"] == 4
        assert main(["wal", "dead-letter", "--wal-dir", d]) == 0
        assert "no dead-letter records" in capsys.readouterr().out
        assert events  # silence the unused-variable lint

    def test_zero_byte_crc_integrity(self, tmp_path):
        """The frame CRC is over the payload — pin the actual zlib
        polynomial so on-disk journals survive module refactors."""
        wal = WriteAheadLog(str(tmp_path / "wal"))
        e = make_event(1)
        payload = encode_record(e, 1, None)
        wal.append(payload)
        wal.close()
        raw = open(os.path.join(str(tmp_path / "wal"),
                                "wal-00000001.seg"), "rb").read()
        length, crc = _HEADER.unpack_from(raw, 0)
        assert length == len(payload)
        assert crc == zlib.crc32(payload)
