"""Scenario test for examples/recommendation-custom-preparator — the
custom-preparator variant (reference:
examples/scala-parallel-recommendation/custom-prepartor): a user-defined
Preparator with its own params drops no-train items from the ratings
before training, so excluded items have no factors and can never be
recommended."""

import os
import sys

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.context import EngineContext
from predictionio_tpu.workflow.persistence import load_models
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples",
    "recommendation-custom-preparator",
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


@pytest.fixture
def storage_with_ratings(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, "CustomPreparatorApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(5)
    for u in range(16):
        for i in range(12):
            if i % 2 == u % 2 and rng.random() < 0.9:
                events.insert(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": 5.0}),
                    ),
                    app_id,
                )
    return storage


def test_shipped_engine_json_binds(example_engine):
    import json

    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        variant = json.load(f)
    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    assert ep.preparator_params[1].filepath == "no_train_items.txt"
    assert ep.algorithm_params_list[0][1].num_iterations == 10


def test_excluded_items_have_no_factors(example_engine, storage_with_ratings,
                                        tmp_path):
    from predictionio_tpu.templates.recommendation import Query

    no_train = tmp_path / "no_train_items.txt"
    no_train.write_text("i0\ni4\n")
    variant = {
        "id": "custom-preparator",
        "engineFactory": "engine.engine_factory",
        "datasource": {"params": {"app_name": "CustomPreparatorApp"}},
        "preparator": {"params": {"filepath": str(no_train)}},
        "algorithms": [
            {"name": "als",
             "params": {"rank": 8, "num_iterations": 8, "lambda_": 0.05,
                        "seed": 1, "use_mesh": False}}
        ],
    }
    storage = storage_with_ratings
    outcome = run_train(variant=variant, storage=storage)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=storage)
    models = eng.prepare_deploy(ctx, ep, load_models(storage, outcome.instance_id))
    _, _, algos, serving = eng.make_components(ep)

    # excluded items are absent from the model's id space entirely
    model = models[0]
    assert "i0" not in model.item_ids and "i4" not in model.item_ids
    assert "i2" in model.item_ids

    # and therefore never appear in any user's recommendations
    for user in ("u0", "u2", "u5"):
        q = serving.supplement(Query(user=user, num=8))
        served = serving.serve(
            q, [a.predict(m, q) for a, m in zip(algos, models)])
        items = [s.item for s in served.item_scores]
        assert "i0" not in items and "i4" not in items

    # an empty exclusion file trains on everything (control)
    no_train.write_text("")
    outcome2 = run_train(variant=variant, storage=storage)
    models2 = eng.prepare_deploy(
        ctx, ep, load_models(storage, outcome2.instance_id))
    assert "i0" in models2[0].item_ids
