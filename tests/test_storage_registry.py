"""Storage registry env parsing + repository wiring tests
(reference behavior: Storage.scala:120-199, 341-363)."""

import pytest

from predictionio_tpu.storage.base import App, Model
from predictionio_tpu.storage.registry import Storage, StorageError


def test_env_parsing_and_wiring(tmp_path):
    env = {
        "PIO_STORAGE_SOURCES_MYSQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_MYSQL_PATH": str(tmp_path / "db.sqlite"),
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "models"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MYSQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MYSQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
    }
    storage = Storage(env)
    storage.verify_all_data_objects()
    app_id = storage.get_meta_data_apps().insert(App(0, "app"))
    assert storage.get_meta_data_apps().get(app_id).name == "app"
    storage.get_model_data_models().insert(Model("m", b"x"))
    assert (tmp_path / "models").exists()
    # clients are cached per source
    assert storage.client_for_source("MYSQL") is storage.client_for_source("MYSQL")
    storage.close()


def test_missing_source_raises():
    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NOPE",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NOPE",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NOPE",
    }
    storage = Storage(env)
    with pytest.raises(StorageError):
        storage.get_meta_data_apps()


def test_partial_repositories_raises(tmp_path):
    env = {
        "PIO_STORAGE_SOURCES_A_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "A",
    }
    with pytest.raises(StorageError):
        Storage(env)


def test_unknown_type_raises():
    env = {
        "PIO_STORAGE_SOURCES_A_TYPE": "martian",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "A",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "A",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "A",
    }
    storage = Storage(env)
    with pytest.raises(StorageError):
        storage.get_events()


def test_default_config_when_env_empty(tmp_path):
    storage = Storage({"PIO_FS_BASEDIR": str(tmp_path)})
    storage.verify_all_data_objects()
    storage.get_events().init(1)
    eid = None
    from predictionio_tpu.core.event import Event

    eid = storage.get_events().insert(
        Event(event="x", entity_type="user", entity_id="u"), 1
    )
    assert storage.get_events().get(eid, 1) is not None
    assert (tmp_path / "pio.sqlite").exists()
    storage.close()


def test_memory_backend_registration():
    env = {
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    }
    storage = Storage(env)
    storage.verify_all_data_objects()
    storage.close()


def test_type_suffixed_property_demoted_to_shorter_source(tmp_path, caplog):
    """A property whose name ends in _TYPE (here FOO_TYPE of source MEM)
    must not spawn a bogus source MEM_FOO when its value is not a
    registered backend type; it stays MEM's property, with a warning."""
    import logging

    env = {
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_MEM_FOO_TYPE": "not-a-backend",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    }
    with caplog.at_level(logging.WARNING, "predictionio_tpu.storage.registry"):
        storage = Storage(env)
    assert "not-a-backend" in caplog.text
    with pytest.raises(StorageError):
        storage.client_for_source("MEM_FOO")
    client = storage.client_for_source("MEM")
    assert client.config.properties.get("FOO_TYPE") == "not-a-backend"
    storage.close()


def test_underscored_source_with_registered_type_still_parses(tmp_path):
    """A genuinely underscored source name whose TYPE is a registered
    backend keeps working even when a shorter source shares its prefix."""
    env = {
        "PIO_STORAGE_SOURCES_PIO_TYPE": "memory",
        "PIO_STORAGE_SOURCES_PIO_SQLITE_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_PIO_SQLITE_PATH": str(tmp_path / "db.sqlite"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "PIO_SQLITE",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PIO",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "PIO",
    }
    storage = Storage(env)
    storage.verify_all_data_objects()
    # PIO must not have swallowed PIO_SQLITE's keys as properties
    assert "SQLITE_TYPE" not in storage.client_for_source("PIO").config.properties
    storage.close()
