"""e2 library tests — categorical NB, Markov chain, binary vectorizer,
cross-validation (modeled on the reference's e2/src/test specs and their
fixtures: NaiveBayesFixture, MarkovChainFixture, BinaryVectorizerFixture)."""

from __future__ import annotations

import math

import numpy as np
import pytest

from predictionio_tpu.e2 import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    LabeledPoint,
    MarkovChain,
    cross_validation_split,
)


# ---------------------------------------------------------------------------
# CategoricalNaiveBayes (reference spec: CategoricalNaiveBayesTest)
# ---------------------------------------------------------------------------

POINTS = [
    LabeledPoint("spam", ("buy", "cheap")),
    LabeledPoint("spam", ("buy", "now")),
    LabeledPoint("spam", ("buy", "cheap")),
    LabeledPoint("ham", ("hello", "friend")),
    LabeledPoint("ham", ("hello", "now")),
]


class TestCategoricalNaiveBayes:
    def test_priors(self):
        model = CategoricalNaiveBayes.train(POINTS)
        assert model.log_priors[model.labels["spam"]] == pytest.approx(math.log(3 / 5))
        assert model.log_priors[model.labels["ham"]] == pytest.approx(math.log(2 / 5))

    def test_likelihoods(self):
        model = CategoricalNaiveBayes.train(POINTS)
        spam = model.labels["spam"]
        buy = model.value_maps[0]["buy"]
        cheap = model.value_maps[1]["cheap"]
        assert model.log_likelihoods[spam, 0, buy] == pytest.approx(math.log(3 / 3))
        assert model.log_likelihoods[spam, 1, cheap] == pytest.approx(math.log(2 / 3))

    def test_log_score_and_predict(self):
        model = CategoricalNaiveBayes.train(POINTS)
        s = model.log_score(LabeledPoint("spam", ("buy", "cheap")))
        assert s == pytest.approx(math.log(3 / 5) + math.log(1.0) + math.log(2 / 3))
        assert model.predict(("buy", "cheap")) == "spam"
        assert model.predict(("hello", "friend")) == "ham"

    def test_unseen_label_scores_none(self):
        model = CategoricalNaiveBayes.train(POINTS)
        assert model.log_score(LabeledPoint("eggs", ("buy", "cheap"))) is None

    def test_unseen_value_default_likelihood(self):
        model = CategoricalNaiveBayes.train(POINTS)
        # default: -inf
        assert model.log_score(LabeledPoint("spam", ("buy", "UNSEEN"))) == -math.inf
        # custom default (reference passes the label's other likelihoods)
        s = model.log_score(
            LabeledPoint("spam", ("buy", "UNSEEN")),
            default_likelihood=lambda ls: min(ls) - math.log(2),
        )
        assert math.isfinite(s)

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            CategoricalNaiveBayes.train([])


# ---------------------------------------------------------------------------
# MarkovChain (reference spec: MarkovChainTest)
# ---------------------------------------------------------------------------

class TestMarkovChain:
    def test_row_normalization_and_topn(self):
        # state 0 -> 1 (3 times), -> 2 (1 time); state 1 -> 2 (2)
        model = MarkovChain.train(
            n_states=3,
            transitions=[(0, 1, 3.0), (0, 2, 1.0), (1, 2, 2.0)],
            top_n=2,
        )
        out = dict(model.predict(0))
        assert out[1] == pytest.approx(0.75)
        assert out[2] == pytest.approx(0.25)
        assert dict(model.predict(1)) == {2: pytest.approx(1.0)}
        assert model.predict(2) == []  # no outgoing transitions

    def test_topn_truncates(self):
        model = MarkovChain.train(
            n_states=4,
            transitions=[(0, j, float(j + 1)) for j in range(1, 4)],
            top_n=2,
        )
        out = model.predict(0)
        assert len(out) == 2
        assert out[0][0] == 3  # highest-probability transition first

    def test_duplicate_transitions_accumulate(self):
        model = MarkovChain.train(
            n_states=2, transitions=[(0, 1, 1.0), (0, 1, 1.0)], top_n=1
        )
        assert dict(model.predict(0)) == {1: pytest.approx(1.0)}


# ---------------------------------------------------------------------------
# BinaryVectorizer (reference spec: BinaryVectorizerTest)
# ---------------------------------------------------------------------------

class TestBinaryVectorizer:
    def test_fit_and_encode(self):
        vec = BinaryVectorizer.fit([("color", "red"), ("color", "blue"), ("size", "L")])
        assert len(vec) == 3
        v = vec.to_binary([("color", "red"), ("size", "L")])
        assert v.sum() == 2.0
        assert v[vec.property_map[("color", "red")]] == 1.0
        assert v[vec.property_map[("size", "L")]] == 1.0

    def test_unknown_pairs_ignored(self):
        vec = BinaryVectorizer.fit([("a", "1")])
        v = vec.to_binary([("a", "1"), ("zz", "99")])
        assert v.tolist() == [1.0]

    def test_batch(self):
        vec = BinaryVectorizer.fit([("a", "1"), ("b", "2")])
        m = vec.to_binary_batch([[("a", "1")], [("b", "2")], []])
        assert m.shape == (3, 2)
        assert m.sum(axis=1).tolist() == [1.0, 1.0, 0.0]


# ---------------------------------------------------------------------------
# cross_validation_split (reference spec: CrossValidationTest)
# ---------------------------------------------------------------------------

class TestCrossValidation:
    def test_folds_partition_data(self):
        data = list(range(10))
        folds = cross_validation_split(
            data, k=3,
            make_training=tuple,
            make_query_actual=lambda d: (d, d * 10),
            eval_info={"name": "cv"},
        )
        assert len(folds) == 3
        all_eval = []
        for td, ei, qa in folds:
            assert ei == {"name": "cv"}
            eval_items = [q for q, _ in qa]
            # training and eval are disjoint and cover everything
            assert set(td) | set(eval_items) == set(data)
            assert set(td) & set(eval_items) == set()
            all_eval.extend(eval_items)
        # each record held out exactly once across folds
        assert sorted(all_eval) == data

    def test_actuals_derived(self):
        folds = cross_validation_split(
            [1, 2], k=2, make_training=list, make_query_actual=lambda d: (d, d * 10)
        )
        assert folds[0][2] == [(1, 10)]
        assert folds[1][2] == [(2, 20)]

    def test_invalid_k(self):
        with pytest.raises(ValueError):
            cross_validation_split([1], k=0, make_training=list,
                                   make_query_actual=lambda d: (d, d))
