"""Evaluation + params-generator classes resolvable by spec string from
the CLI eval test (`pio eval tests.cli_eval_support.CliEvaluation ...`)."""

from __future__ import annotations

from predictionio_tpu.controller import (
    AverageMetric,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
)

from tests.sample_engine import AlgoParams, DSParams, make_engine


class ValueMetric(AverageMetric):
    def calculate_qpa(self, q, p, a):
        return float(p.value)


class CliEvaluation(Evaluation):
    def __init__(self):
        super().__init__()
        self.engine_evaluator = (make_engine(), MetricEvaluator(ValueMetric()))


class CliParamsList(EngineParamsGenerator):
    def __init__(self):
        super().__init__([
            EngineParams.of(
                data_source=DSParams(id=1, n_train=4, n_folds=2),
                algorithms=[("sample", AlgoParams(id=0, mult=m))],
            )
            for m in (1, 2)
        ])


def run_target(*args):
    """Target for the `pio run` CLI test."""
    print(f"run_target({', '.join(args)})")
    return 0
