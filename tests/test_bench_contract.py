"""bench.py is the driver's interface (BENCH_r{N}.json): its ONE-line
JSON contract must not regress. This smoke test runs the real ALS and
ingest sections at tiny scale on the CPU backend and stubs the
device-heavy sections (serving/quality/seqrec run for minutes at real
shapes), asserting the primary keys and the partial-failure guard."""

import json

import pytest


@pytest.fixture
def tiny_bench(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "USERS", 120)
    monkeypatch.setattr(bench, "ITEMS", 60)
    monkeypatch.setattr(bench, "NNZ", 3000)
    monkeypatch.setattr(bench, "SUB_NNZ", 1000)
    monkeypatch.setattr(bench, "N_SHORT", 1)
    monkeypatch.setattr(bench, "N_LONG", 3)
    monkeypatch.setattr(bench, "bench_serving",
                        lambda *a, **kw: {"p50_ms": 1.0, "p99_ms": 2.0})
    # serving_path drives a real HTTP server fleet at 100k-item scale
    # (bench_serving.py) — stubbed like the other device-heavy sections
    monkeypatch.setattr(bench, "bench_serving_path",
                        lambda: {"serving_speedup_x": 2.0})
    monkeypatch.setattr(bench, "bench_quality",
                        lambda: {"map10_tpu": 0.1, "map10_ref": 0.1})
    monkeypatch.setattr(bench, "bench_seqrec",
                        lambda: {"seqrec_tokens_per_sec": 1.0})
    # device-heavy r3 sections (pallas interpret mode on CPU is minutes;
    # rank 200 is PFLOP-scale at real shapes)
    monkeypatch.setattr(bench, "bench_rank200",
                        lambda *a, **kw: {"rank200_rate": 1.0})
    monkeypatch.setattr(bench, "bench_attention",
                        lambda *a, **kw: {"flash_s4096_ms": 1.0})
    # keep ingest real but tiny (default posts 2000+warmup events)
    real_ingest = bench.bench_ingest
    monkeypatch.setattr(bench, "bench_ingest",
                        lambda: real_ingest(n_events=100, batch=25))
    # data_plane spawns client subprocesses and scans 10k+ events
    # (bench_ingest.py) — stubbed here, covered by its own perf test
    monkeypatch.setattr(bench, "bench_data_plane",
                        lambda: {"scan_speedup_x_sqlite": 3.0,
                                 "ingest_tx_speedup_x": 2.0,
                                 "wal_interval_vs_direct_x": 1.0})
    # ann_retrieval builds IVF indexes and drives HTTP server pairs at
    # catalog scale (bench_serving.py) — stubbed here; the shrunk
    # harness itself is covered by the --skip-heavy artifact runs.
    # The stub mirrors the REAL key naming (suffix = items//1000):
    # full runs emit 100k/1000k keys, shrunk runs emit 16k keys.
    monkeypatch.setattr(
        bench, "bench_ann_retrieval",
        lambda shrunk=False: ({"ann_speedup_16k_x": 1.0,
                               "ann_recall_16k": 0.99} if shrunk else
                              {"ann_speedup_100k_x": 1.0,
                               "ann_recall_100k": 0.99}))
    # workers_scaling spawns engine-server process pools over
    # SO_REUSEPORT (bench_serving.py --workers-only) — stubbed here;
    # the real tiny harness is the slow-marked test below
    monkeypatch.setattr(
        bench, "bench_workers_scaling",
        lambda shrunk=False: {"workers_scaling_2w_vs_1w_x": 1.0,
                              "workers_qps_1w": 100.0,
                              "workers_qps_2w": 160.0,
                              "workers_host_cores": 2,
                              "workers_reported_in_merged_metrics": 2.0})
    # shm_cache spawns paired private-vs-shm serving pools over one
    # POSIX segment (bench_serving.py --shm-only) — stubbed here; the
    # real tiny harness is the slow-marked test below
    monkeypatch.setattr(
        bench, "bench_shm_cache",
        lambda shrunk=False: {"shm_qps_1w_private": 100.0,
                              "shm_qps_1w_shm": 98.0,
                              "shm_qps_2w_private": 150.0,
                              "shm_qps_2w_shm": 148.0,
                              "shm_hit_ratio_2w_private": 0.9,
                              "shm_hit_ratio_2w_shm": 0.95,
                              "shm_rewarm_misses_2w_private": 24,
                              "shm_rewarm_misses_2w_shm": 8,
                              "shm_p99_ms_2w_private": 5.0,
                              "shm_p99_ms_2w_shm": 5.0,
                              "shm_host_cores": 2,
                              "shm_host_cores_caveat": None})
    # freshness trains + deploys a live server fleet (bench_freshness.py)
    # — stubbed here; the real tiny harness is the perf test below
    monkeypatch.setattr(
        bench, "bench_freshness_section",
        lambda shrunk=False: {"freshness_lag_p50_ms": 300.0,
                              "freshness_foldin_events_per_sec": 100.0,
                              "freshness_http_5xx": 0})
    # gateway spawns a replica fleet + two router subprocesses
    # (bench_serving.py --gateway-only) — stubbed here; the real tiny
    # harness is the slow-marked test below
    monkeypatch.setattr(
        bench, "bench_gateway_phase",
        lambda shrunk=False: {"gateway_quota_neighbor_p99_ratio_x": 1.0,
                              "gateway_two_engine_overhead_pct": 0.5,
                              "gateway_throttled_429": 100,
                              "gateway_http_5xx": 0,
                              "gateway_host_cores": 2})
    # elasticity drives live router threads + a ManualClock timeline
    # (bench_elasticity.py) — stubbed here; the real tiny harness is
    # the slow-marked test below
    monkeypatch.setattr(
        bench, "bench_elasticity_section",
        lambda shrunk=False: {"elasticity_compliant_p99_ratio_x": 1.0,
                              "elasticity_b_http_5xx": 0,
                              "elasticity_throttled_429": 100,
                              "elasticity_burst_admitted_with_credits": 21,
                              "elasticity_burst_admitted_control": 5,
                              "elasticity_host_cores": 2,
                              "elasticity_host_cores_caveat": None})
    # experiment forks eval worker children for the grid 1-vs-N ratio
    # (bench_experiment.py) — stubbed here; the real tiny harness is
    # the slow-marked test below
    monkeypatch.setattr(
        bench, "bench_experiment_section",
        lambda shrunk=False: {"experiment_grid_speedup_x": 1.0,
                              "experiment_grid_points": 4,
                              "experiment_grid_parallel": 2,
                              "experiment_grid_seq_s": 0.4,
                              "experiment_grid_par_s": 0.4,
                              "experiment_grid_failed_points": 0,
                              "experiment_assign_ops_per_s": 10_000.0,
                              "experiment_host_cores": 2,
                              "experiment_host_cores_caveat": None})
    # train_sharding spawns a forced-8-device jax subprocess child
    # (bench_sharding.py) — stubbed here; the real tiny harness is the
    # slow-marked test below
    monkeypatch.setattr(
        bench, "bench_train_sharding",
        lambda shrunk=False: {
            "train_sharding_devices": 8,
            "train_sharding_model_axis": 2,
            "train_sharding_replicated_mfu": None,
            "train_sharding_sharded_mfu": None,
            "train_sharding_replicated_hbm_peak_bytes": None,
            "train_sharding_sharded_hbm_peak_bytes": None,
            "train_sharding_replicated_table_bytes_per_device": 5120,
            "train_sharding_sharded_table_bytes_per_device": 2560,
            "train_sharding_parity_max_abs_diff": 0.0,
            "train_sharding_r512_completed": True,
            "train_sharding_r512_fits_replicated": False,
            "train_sharding_r512_fits_sharded": True})
    # keep calibration real but tiny (2048^3 bf16 chains are for the chip)
    real_calib = bench.bench_calibration
    monkeypatch.setattr(bench, "bench_calibration",
                        lambda: real_calib(n=128, rounds=2))
    return bench


def test_single_json_line_with_primary_contract(tiny_bench, capsys, monkeypatch):
    monkeypatch.setattr("sys.argv", ["bench.py"])
    tiny_bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "bench must print exactly ONE line"
    line = json.loads(out[0])
    # the driver's primary contract
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in line, key
    assert line["unit"] == "ratings/sec"
    assert line["value"] > 0 and line["vs_baseline"] > 0
    # round-over-round comparison keys
    for key in ("stdev_pct", "iter_ms", "padding_x", "p50_ms",
                "map10_tpu", "seqrec_tokens_per_sec",
                "ingest_events_per_sec", "ingest_events_per_sec_stdev_pct",
                "calibration_matmul_ms", "scan_speedup_x_sqlite",
                "ingest_tx_speedup_x", "ann_speedup_100k_x",
                "workers_scaling_2w_vs_1w_x", "workers_host_cores",
                "freshness_lag_p50_ms",
                "freshness_foldin_events_per_sec",
                # the multi-tenant gateway trajectory keys (PR 15)
                "gateway_quota_neighbor_p99_ratio_x",
                "gateway_two_engine_overhead_pct",
                "gateway_throttled_429", "gateway_http_5xx",
                # the per-tenant elasticity trajectory keys (PR 16)
                "elasticity_compliant_p99_ratio_x",
                "elasticity_b_http_5xx", "elasticity_throttled_429",
                "elasticity_burst_admitted_with_credits",
                "elasticity_host_cores_caveat",
                # the experimentation-platform trajectory keys (PR 20)
                "experiment_grid_speedup_x",
                "experiment_grid_failed_points",
                "experiment_assign_ops_per_s",
                "experiment_host_cores_caveat",
                # the shared-memory serving-plane trajectory keys (PR 18)
                "shm_qps_2w_private", "shm_qps_2w_shm",
                "shm_hit_ratio_2w_shm", "shm_rewarm_misses_2w_private",
                "shm_rewarm_misses_2w_shm", "shm_host_cores_caveat",
                # train_profile runs REAL (tiny train, seconds): the
                # device/compiler observability trajectory keys
                "train_profile_mfu", "train_profile_compile_seconds",
                "train_profile_compiles", "train_profile_wall_seconds",
                # the DP×MP factor-sharding trajectory keys (PR 19)
                "train_sharding_devices", "train_sharding_model_axis",
                "train_sharding_parity_max_abs_diff",
                "train_sharding_replicated_table_bytes_per_device",
                "train_sharding_sharded_table_bytes_per_device",
                "train_sharding_r512_completed",
                "train_sharding_r512_fits_sharded"):
        assert key in line, key
    # MFU is honest-or-nothing: a float when a peak is known, else
    # null — never absent, never fabricated
    assert line["train_profile_mfu"] is None \
        or isinstance(line["train_profile_mfu"], float)
    assert line["train_profile_compiles"] >= 1
    # a complete artifact says so explicitly (VERDICT r4 weak #7)
    assert line["sections_failed"] == []


def test_section_failure_keeps_primary_metric(tiny_bench, capsys, monkeypatch):
    """A crashing section must surface as error_<name>, never lose the
    headline metric (the driver records whatever line is printed)."""
    monkeypatch.setattr("sys.argv", ["bench.py"])
    monkeypatch.setattr(
        tiny_bench, "bench_quality",
        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    tiny_bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] > 0
    assert "error_quality" in line and "boom" in line["error_quality"]
    assert "map10_tpu" not in line
    # the hole in the contract is marked at the artifact top level
    assert line["sections_failed"] == ["quality"]


def test_skip_heavy_lists_skipped_sections(tiny_bench, capsys, monkeypatch):
    """--skip-heavy artifacts are INCOMPLETE and must say so: the
    skipped sections land in sections_failed (README contract)."""
    monkeypatch.setattr("sys.argv", ["bench.py", "--skip-heavy"])
    tiny_bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert set(line["sections_failed"]) == {
        "phases", "rank200", "serving", "serving_path", "attention",
        "seqrec"}
    assert "ingest_events_per_sec" in line and "map10_tpu" in line
    assert "scan_speedup_x_sqlite" in line   # data_plane runs skip-heavy
    assert "wal_interval_vs_direct_x" in line  # WAL phase rides data_plane
    assert "ann_speedup_16k_x" in line       # ann_retrieval runs SHRUNK
    # workers_scaling runs SHRUNK under --skip-heavy too
    assert "workers_scaling_2w_vs_1w_x" in line
    # freshness runs SHRUNK under --skip-heavy too
    assert "freshness_lag_p50_ms" in line
    # gateway runs SHRUNK under --skip-heavy too
    assert "gateway_quota_neighbor_p99_ratio_x" in line
    # elasticity runs SHRUNK under --skip-heavy too
    assert "elasticity_compliant_p99_ratio_x" in line
    # experiment runs SHRUNK under --skip-heavy too
    assert "experiment_grid_speedup_x" in line
    # shm_cache runs SHRUNK under --skip-heavy too
    assert "shm_rewarm_misses_2w_shm" in line


@pytest.mark.perf
def test_data_plane_harness_contract_tiny():
    """bench_ingest.py's real phases at tiny scale: the scan harness
    must verify row/columnar output equivalence before timing (it
    asserts internally), and the DAO ingest section must report both
    rates plus the ratio. The HTTP section spawns subprocesses and is
    exercised by the full artifact runs, not here."""
    import bench_ingest

    scan = bench_ingest.bench_scan(n_events=1200, rounds=1)
    for kind in ("memory", "sqlite"):
        assert scan[f"scan_row_events_per_sec_{kind}"] > 0
        assert scan[f"scan_columnar_events_per_sec_{kind}"] > 0
    dao = bench_ingest.bench_ingest_dao(n_events=300, batch=50, rounds=1)
    assert dao["ingest_per_event_events_per_sec"] > 0
    assert dao["ingest_batch_tx_events_per_sec"] > 0
    assert dao["ingest_tx_speedup_x"] > 0
    # the WAL phase (PR 13) reports every fsync policy plus the ratio
    # against direct insert — the keys BENCH_wal_rNN.json records
    wal = bench_ingest.bench_wal(n_events=300, batch=50, rounds=1)
    for policy in ("off", "interval", "always"):
        assert wal[f"wal_append_{policy}_events_per_sec"] > 0
        assert wal[f"wal_{policy}_vs_direct_x"] > 0
    assert wal["wal_direct_batch_events_per_sec"] > 0


@pytest.mark.perf
@pytest.mark.slow
@pytest.mark.online
def test_freshness_harness_contract_tiny():
    """bench_freshness.py's real harness at tiny scale: trains, deploys
    --online single + 2-worker-spool fleets in process, probes the
    event→serve lag, and must report the lag distribution, fold-in
    throughput, the workers-variant lag, and ZERO 5xx (the keys
    BENCH_freshness_rNN.json records). Slow-marked: one tiny train +
    three live servers."""
    import bench_freshness

    r = bench_freshness.bench_freshness(
        n_users=12, n_items=10, probe_rounds=2, foldin_events=60,
        workers_rounds=1)
    assert r["freshness_lag_p50_ms"] > 0
    assert r["freshness_foldin_events_per_sec"] > 0
    assert r["freshness_workers_lag_p50_ms"] > 0
    assert r["freshness_http_5xx"] == 0
    assert r["freshness_http_requests"] > 0


@pytest.mark.perf
@pytest.mark.slow
@pytest.mark.fleet
def test_gateway_harness_contract_tiny():
    """bench_serving.py's real gateway phase at tiny scale: spawns the
    2-replica fleet plus the one-engine and two-engine router
    subprocesses, drives both tenants concurrently, throttles tenant
    ``rec`` at runtime, and must report the neighbor-p99 ratio, the
    table-cost delta, a non-zero 429 count for the throttled tenant,
    and ZERO 5xx (the keys BENCH_gateway_rNN.json records).
    Slow-marked: three jax-importing child processes."""
    import bench_serving

    r = bench_serving.bench_gateway(
        items=4096, clients=4, per_client=8, rounds=2,
        quota_qps=5.0)
    assert r["value"] is not None and r["value"] > 0
    assert r["single_engine_qps"] > 0 and r["two_engine_qps"] > 0
    assert r["throttled_429"] > 0
    assert r["rec_quota_throttled_total"] > 0
    assert r["ecom_quota_throttled_total"] == 0
    assert r["http_5xx"] == 0
    assert r["host_cores"] >= 1


@pytest.mark.perf
@pytest.mark.slow
@pytest.mark.elasticity
def test_elasticity_harness_contract_tiny():
    """bench_elasticity.py's real harness at tiny scale: live router +
    echo backends for the isolation and burst-credit phases, a
    ManualClock EngineScaleSet for the timeline phase. Must report the
    compliant-tenant ratio with ZERO 5xx, a throttled abusive tenant,
    more burst admissions with credits than without, a non-empty
    per-engine decision timeline, and the 1-core caveat contract (the
    keys BENCH_elasticity_rNN.json records). Slow-marked: live HTTP
    rounds plus a deliberate credit-accrual idle."""
    import os

    import bench_elasticity

    r = bench_elasticity.bench_elasticity(
        rounds=1, b_requests=20, idle_s=1.0, ticks=12)
    assert r["value"] > 0
    assert r["b_http_5xx"] == 0
    assert r["a_throttled_429"] > 0
    assert r["burst_admitted_with_credits"] > r["burst_admitted_control"]
    assert r["burst_credit_spends"] > 0
    assert r["scale_timeline"], "timeline must record scale decisions"
    assert set(r["scale_decisions"]) == {"diurnal", "spiky", "abusive"}
    # honest 1-core caveat: present exactly when the host is too small
    # for multi-process ratios to be pins
    cores = os.cpu_count() or 1
    if cores < 2:
        assert r["host_cores_caveat"] and "NOT a pin" in r["host_cores_caveat"]
    else:
        assert r["host_cores_caveat"] is None


@pytest.mark.perf
@pytest.mark.slow
@pytest.mark.experiment
def test_experiment_harness_contract_tiny():
    """bench_experiment.py's real harness at tiny scale: the same grid
    through run_parallel_grid at width 1 and width 2 (zero failed
    points on a healthy grid), plus the assign()/record() loop, with
    the honest 1-core caveat contract (the keys
    BENCH_experiment_rNN.json records). Slow-marked: deliberate
    per-point CPU burn times the grid twice."""
    import os

    import bench_experiment

    r = bench_experiment.bench_experiment(points=3, parallel=2,
                                          work_ms=10.0, ops=2_000)
    assert r["grid"]["value"] > 0
    assert r["grid"]["failed_points"] == 0
    assert r["grid"]["seq_s"] > 0 and r["grid"]["par_s"] > 0
    assert r["assign"]["value"] > 0
    cores = os.cpu_count() or 1
    if cores < 2:
        assert r["host_cores_caveat"] and "NOT a pin" in r["host_cores_caveat"]
    else:
        assert r["host_cores_caveat"] is None


@pytest.mark.perf
@pytest.mark.slow
@pytest.mark.shm
def test_shm_harness_contract_tiny():
    """bench_serving.py's real shm phase at tiny scale: spawns the
    paired private-LRU and shared-segment pools at 1 and 2 workers,
    drives the cached workload, scrapes the pool-wide hit ratio, and
    runs the post-invalidation rewarm probe. The shared arm must pay
    each probed key AT MOST what the private arm pays — sharing one
    physical cache can only reduce pool-wide cold misses (the keys
    BENCH_shm_rNN.json records). Slow-marked: four jax-importing
    child processes."""
    import bench_serving

    r = bench_serving.bench_shm(
        items=4096, clients=4, per_client=4, rounds=2, procs=1,
        rewarm_keys=6)
    assert r["value"] is not None and r["value"] > 0
    assert r["host_cores"] >= 1
    by_workers = {e["workers"]: e for e in r["per_workers"]}
    for n in (1, 2):
        e = by_workers[n]
        assert e["private_qps"] > 0 and e["shm_qps"] > 0
        assert e["private_errors"] == 0 and e["shm_errors"] == 0
        assert e["shm_hit_ratio"] is not None and e["shm_hit_ratio"] > 0
        # every probed key misses at least once (the invalidation took)
        # and the shared segment never pays MORE than replicas do
        assert e["shm_rewarm_misses"] >= r["rewarm_keys"]
        assert e["shm_rewarm_misses"] <= e["private_rewarm_misses"]
    # 1 worker: private and shm are the same topology — both pay each
    # probed key exactly once
    assert by_workers[1]["shm_rewarm_misses"] == r["rewarm_keys"]


@pytest.mark.perf
@pytest.mark.slow
def test_workers_harness_contract_tiny():
    """bench_serving.py's real workers phase at tiny scale: spawns the
    1-worker and 2-worker SO_REUSEPORT pools as subprocesses, drives a
    handful of queries, and must report the scaling ratio, per-pool
    qps, host cores, and the merged-scrape worker count (the harness
    sanity the full artifact runs depend on). Slow-marked: three
    jax-importing child processes."""
    import bench_serving

    r = bench_serving.bench_workers(
        items=4096, clients=4, per_client=4, rounds=2, procs=1,
        ann_items=None)
    assert r["value"] > 0
    assert r["qps_1w"] > 0 and r["qps_2w"] > 0
    assert r["host_cores"] >= 1
    assert r["workers_reported_in_merged_metrics"] == 2.0
    assert r["errors"] == 0


@pytest.mark.mesh
@pytest.mark.slow
def test_sharding_harness_contract_tiny():
    """bench_sharding.py's real child at tiny (shrunk) scale: one
    forced-8-device subprocess running replicated-vs-sharded matched
    shapes through `pio train --profile` plus the sharded-only point —
    the keys and invariants BENCH_sharding_rNN.json records.
    Slow-marked: a jax-importing child training four models."""
    import bench_sharding

    r = bench_sharding.bench_sharding_section(shrunk=True)
    assert r["train_sharding_devices"] == 8
    assert r["train_sharding_model_axis"] >= 2
    # the parity number IS the numerics claim: sharded == replicated
    assert r["train_sharding_parity_max_abs_diff"] <= 2e-4
    # per-device table bytes shrink by exactly the model axis
    assert (r["train_sharding_sharded_table_bytes_per_device"]
            == r["train_sharding_replicated_table_bytes_per_device"]
            // r["train_sharding_model_axis"])
    # MFU/HBM are honest-or-null (CPU backend: null)
    for key in ("train_sharding_replicated_mfu",
                "train_sharding_sharded_mfu"):
        assert r[key] is None or isinstance(r[key], float)
    assert r["train_sharding_r512_completed"] is True
    assert r["train_sharding_r512_fits_sharded"] is True
    assert (r["train_sharding_r512_sharded_table_bytes_per_device"]
            == r["train_sharding_r512_replicated_table_bytes"]
            // r["train_sharding_devices"])
