"""Scenario test for examples/similarproduct-add-rateevent — the
reference's add-rateevent variant: rate events with values, keep-latest
dedup per (user, item), explicit ALS training. Driven through the real
train workflow and HTTP serving."""

import datetime
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples",
    "similarproduct-add-rateevent",
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


@pytest.fixture
def seeded_storage(storage):
    """Two taste communities rating 16 items 1-5."""
    app_id = storage.get_meta_data_apps().insert(App(0, "RateEventApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(17)
    for u in range(20):
        for i in range(16):
            if rng.random() < 0.7:
                liked = i % 2 == u % 2
                rating = float(rng.integers(4, 6) if liked
                               else rng.integers(1, 3))
                events.insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap({"rating": rating})),
                    app_id)
    return storage


def _variant():
    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        variant = json.load(f)
    variant["algorithms"][0]["params"]["use_mesh"] = False
    return variant


def test_keep_latest_rating_per_pair(example_engine, seeded_storage):
    """A re-rate REPLACES the old value (reference reduceByKey on event
    time, ALSAlgorithm.scala:105-113) — verified at the DataSource."""
    app = seeded_storage.get_meta_data_apps().get_by_name("RateEventApp")
    t0 = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)
    for day, rating in ((0, 1.0), (1, 2.0), (2, 5.0)):
        seeded_storage.get_events().insert(
            Event(event="rate", entity_type="user", entity_id="fickle",
                  target_entity_type="item", target_entity_id="i0",
                  properties=DataMap({"rating": rating}),
                  event_time=t0 + datetime.timedelta(days=day)),
            app.id)
    from predictionio_tpu.workflow.context import EngineContext

    ds = example_engine.RateEventDataSource(
        example_engine.RateEventDataSource.params_class(
            app_name="RateEventApp"))
    td = ds.read_training(EngineContext(storage=seeded_storage))
    sel = [(u, i, r) for u, i, r in zip(td.users, td.items, td.ratings)
           if u == "fickle"]
    assert sel == [("fickle", "i0", 5.0)], sel


def test_explicit_rate_training_and_serving(example_engine, seeded_storage):
    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.workflow.context import EngineContext
    from predictionio_tpu.workflow.deploy import (
        DeployedEngine,
        ServerConfig,
    )
    from predictionio_tpu.workflow.persistence import load_models

    variant = _variant()
    outcome = run_train(variant=variant, storage=seeded_storage)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=seeded_storage)
    _, _, algos, serving = eng.make_components(ep)
    models = eng.prepare_deploy(
        ctx, ep, load_models(seeded_storage, outcome.instance_id),
        algorithms=algos)

    instance = seeded_storage.get_meta_data_engine_instances().get(
        outcome.instance_id)
    server = EngineServer(
        DeployedEngine(None, instance, algos, serving, models),
        ServerConfig(ip="127.0.0.1", port=0))
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/queries.json",
            data=json.dumps({"items": ["i2"], "num": 4}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            scores = json.loads(r.read())["itemScores"]
        recs = [s["item"] for s in scores]
        assert len(recs) == 4
        assert "i2" not in recs        # query item excluded
        # explicit ratings separate the taste communities: items liked
        # by the same (even) community dominate similar-to-i2 results
        even = sum(1 for i in recs if int(i[1:]) % 2 == 0)
        assert even >= 3, recs
    finally:
        server.stop()
