"""The experimentation-platform acceptance pin (ISSUE 20).

A live router splits bare /queries.json traffic across deployed
variants; the breaching variant auto-aborts, the healthy one
auto-promotes to the gateway default with ZERO 5xx on the survivor;
served responses carry experiment/variant attribution; conversion
events swept from the event store fold into the online score; and a
promotion decided in one ``--workers`` sibling survives both sibling
adoption and a fresh respawn via the admin spool.

Echo-replica + router plumbing reused from tests/test_fleet_router.py.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.api.router_server import RouterServer
from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.experiment.cli import sweep_conversions
from predictionio_tpu.fleet.gateway import EngineSpec
from predictionio_tpu.fleet.router import RouterConfig

from tests.netutil import wait_until
from tests.test_fleet_router import (
    echo_server,
    get_json,
    get_metrics,
    post_query,
)

pytestmark = pytest.mark.experiment


def experiments_post(port: int, payload: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/fleet/experiments",
        data=json.dumps(payload).encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _experiment_doc(**overrides) -> dict:
    doc = {"name": "exp", "rampS": 0.0, "measureS": 1.0,
           "minRequests": 10, "conversionWeight": 0.5,
           "guardrail": {"minRequests": 5, "maxErrorRate": 0.4,
                         "maxP99Ms": 0.0, "window": 50}}
    doc.update(overrides)
    return doc


def _snapshot(port: int) -> dict | None:
    status, doc = get_json(port, "/fleet/experiments")
    assert status == 200
    return doc.get("experiment")


class TestExperimentE2E:
    def test_abort_promote_attribution_zero_5xx_on_survivor(self):
        good = echo_server("good0")
        bad = echo_server("bad0", fail=True)
        base = echo_server("base0")
        router = RouterServer(RouterConfig(
            ip="127.0.0.1", port=0,
            engines=(
                EngineSpec(name="base",
                           backends=(f"127.0.0.1:{base.port}",)),
                EngineSpec(name="expA",
                           backends=(f"127.0.0.1:{good.port}",)),
                EngineSpec(name="expB",
                           backends=(f"127.0.0.1:{bad.port}",)),
            ),
            default_engine="base", probe_interval_s=0.25,
            admin_sync_interval_s=0.1))
        router.start()
        try:
            wait_until(lambda: post_query(router.port, {"q": 0})[0] == 200,
                       timeout=10.0, message="fleet is up")

            # a variant that is not a registered engine is refused
            status, doc = experiments_post(router.port, {
                "action": "define", "experiment": _experiment_doc(),
                "variants": [{"name": "ghost", "weightPct": 100}]})
            assert status == 400
            assert "not registered engines" in doc["message"]

            status, _ = experiments_post(router.port, {
                "action": "define", "experiment": _experiment_doc(),
                "variants": [
                    {"name": "expA", "weightPct": 50, "gridIdx": 0,
                     "offlineScore": 3.0},
                    {"name": "expB", "weightPct": 50, "gridIdx": 1,
                     "offlineScore": 2.0}]})
            assert status == 200

            # live traffic: bare-path queries split across variants,
            # every assigned response carries the attribution stamp
            survivor_5xx = 0
            attributed = set()
            for i in range(300):
                s, body, hdrs = post_query(router.port, {"q": i})
                variant = hdrs.get("x-pio-variant")
                if variant:
                    assert hdrs.get("x-pio-experiment") == "exp"
                    attributed.add(variant)
                    if variant == "expA":
                        assert s == 200
                        # the replica stamped the body via the
                        # forwarded attribution headers
                        assert body["experimentId"] == "exp"
                        assert body["variantId"] == "expA"
                        assert body["tag"] == "good0"
                        if s >= 500:
                            survivor_5xx += 1
                snap = _snapshot(router.port)
                aborted = {v["name"] for v in snap["variants"]
                           if v["aborted"]}
                if aborted:
                    break
            assert attributed >= {"expA", "expB"}
            assert aborted == {"expB"}
            assert survivor_5xx == 0

            # conversions fold into the online score while measuring
            status, doc = experiments_post(router.port, {
                "action": "conversions", "experiment": "exp",
                "conversions": {"expA": 5}})
            assert status == 200
            expa = {v["name"]: v
                    for v in doc["experiment"]["variants"]}["expA"]
            assert expa["conversions"] == 5
            # (1-w)*success + w*conv_rate with a clean success record:
            # the conversion term pushes the score above 0.5
            assert expa["onlineScore"] > 0.5

            # keep traffic flowing until the measure window closes and
            # the survivor is promoted
            def promoted():
                s, _, _ = post_query(router.port, {"q": "tick"})
                snap = _snapshot(router.port)
                return snap["state"] == "PROMOTED"
            wait_until(promoted, timeout=15.0,
                       message="survivor promoted to default")

            snap = _snapshot(router.port)
            assert snap["decision"]["winner"] == "expA"
            assert {v["name"]: v["conversions"]
                    for v in snap["variants"]}["expA"] == 5

            # promotion on the gateway: expA is the default engine,
            # the loser is retired, bare traffic serves the winner
            # with zero 5xx and no further experiment assignment
            status, doc = get_json(router.port, "/fleet/engines")
            assert doc["defaultEngine"] == "expA"
            names = {e["name"] for e in doc["engines"]}
            assert "expB" not in names
            s, body, hdrs = post_query(router.port, {"q": "after"})
            assert (s, body["tag"]) == (200, "good0")
            assert "x-pio-variant" not in hdrs

            # the scrape contract: state gauge + conversion counters
            text = get_metrics(router.port)
            assert 'pio_experiment_state{' in text
            assert ('pio_experiment_conversions_total{experiment="exp",'
                    'variant="expA"} 5' in text)
            assert "pio_eval_points_total" in text
        finally:
            router.stop()
            for s in (good, bad, base):
                s.stop()


class TestPromotionSurvivesWorkers:
    def test_spool_carries_verdict_to_sibling_and_respawn(self):
        """A promotion decided in ONE worker reaches its sibling's sync
        loop AND a freshly respawned worker — gateway default included
        (the decision must not evaporate with the process that took it)."""
        good = echo_server("good0")
        base = echo_server("base0")
        spool = tempfile.mkdtemp(prefix="pio-test-experiment-")

        def mk():
            return RouterServer(RouterConfig(
                ip="127.0.0.1", port=0,
                engines=(
                    EngineSpec(name="base",
                               backends=(f"127.0.0.1:{base.port}",)),
                    EngineSpec(name="expA",
                               backends=(f"127.0.0.1:{good.port}",)),
                ),
                default_engine="base", worker_spool_dir=spool,
                probe_interval_s=0.25, admin_sync_interval_s=0.1))

        w1 = mk()
        w2 = mk()
        w1.start()
        w2.start()
        w3 = None
        try:
            wait_until(lambda: post_query(w1.port, {"q": 0})[0] == 200,
                       timeout=10.0, message="fleet is up")
            status, _ = experiments_post(w1.port, {
                "action": "define",
                "experiment": _experiment_doc(measureS=0.0, minRequests=1),
                "variants": [{"name": "expA", "weightPct": 100}]})
            assert status == 200

            def w1_promoted():
                s, _, _ = post_query(w1.port, {"q": "x"})
                snap = _snapshot(w1.port)
                return snap is not None and snap["state"] == "PROMOTED"
            wait_until(w1_promoted, timeout=15.0,
                       message="w1 promoted the lone healthy variant")

            def sibling_adopted():
                snap = _snapshot(w2.port)
                return (snap is not None
                        and snap["state"] == "PROMOTED"
                        and w2.gateway.default_engine == "expA")
            wait_until(sibling_adopted, timeout=10.0,
                       message="sibling adopted the promotion")

            # a respawned worker boots with the verdict AND the
            # promoted gateway table
            w3 = mk()
            w3.start()
            snap = _snapshot(w3.port)
            assert snap["state"] == "PROMOTED"
            assert snap["decision"]["winner"] == "expA"
            assert w3.gateway.default_engine == "expA"
            s, body, _ = post_query(w3.port, {"q": "respawn"})
            assert (s, body["tag"]) == (200, "good0")
        finally:
            for w in (w1, w2, w3):
                if w is not None:
                    w.stop()
            good.stop()
            base.stop()
            shutil.rmtree(spool, ignore_errors=True)


class TestConversionSweep:
    def test_event_store_sweep_counts_attributed_non_predict(self, storage):
        events = storage.get_events()

        def put(event, props, app_id=1):
            events.insert(Event(event=event, entity_type="user",
                                entity_id="u1",
                                properties=DataMap(props)), app_id)

        put("buy", {"experimentId": "exp", "variantId": "expA"})
        put("buy", {"experimentId": "exp", "variantId": "expA"})
        put("click", {"experimentId": "exp", "variantId": "expB"})
        # excluded: the server's own feedback events, foreign
        # experiments, unattributed events, other apps
        put("predict", {"experimentId": "exp", "variantId": "expA"})
        put("buy", {"experimentId": "other", "variantId": "expA"})
        put("buy", {})
        put("buy", {"experimentId": "exp", "variantId": "expA"}, app_id=2)

        assert sweep_conversions(storage, 1, "exp") \
            == {"expA": 2, "expB": 1}
        assert sweep_conversions(storage, 2, "exp") == {"expA": 1}
        assert sweep_conversions(storage, 3, "exp") == {}
