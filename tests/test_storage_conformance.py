"""Storage-backend conformance suite.

One spec, every backend — the reference runs the same LEventsSpec /
PEventsSpec against each live store (reference: storage/jdbc/src/test/...,
storage/hbase/src/test/...; SURVEY.md §4.2). Parameterized here over the
in-memory and sqlite backends (and sqlite-on-disk via tmp_path).
"""

from datetime import datetime, timedelta, timezone

import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
    Model,
    StorageClientConfig,
)
from predictionio_tpu.storage.memory import MemoryStorageClient
from predictionio_tpu.storage.sqlite import SQLiteStorageClient
from predictionio_tpu.utils.testing import sqlite_supports_returning

T0 = datetime(2020, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="session")
def pg_emulator():
    """One wire-protocol emulator for the whole session; tests isolate
    by database name (pg_emulator.py gives each database its own
    store)."""
    from pg_emulator import PGEmulator

    with PGEmulator(password="conf-pw") as emu:
        yield emu


def _pg_client(emu):
    import uuid

    from predictionio_tpu.storage.postgres import PGStorageClient

    return PGStorageClient(StorageClientConfig(properties={
        "HOST": "127.0.0.1", "PORT": str(emu.port),
        "USERNAME": "pio", "PASSWORD": "conf-pw",
        "DATABASE": f"conf_{uuid.uuid4().hex[:12]}",
    }))


@pytest.fixture(params=["memory", "sqlite", "sqlite_file", "postgres"])
def client(request, tmp_path, pg_emulator):
    if request.param == "memory":
        c = MemoryStorageClient()
    elif request.param == "sqlite":
        c = SQLiteStorageClient(StorageClientConfig(test=True))
    elif request.param == "postgres":
        # the full metadata/model conformance surface over the REAL
        # wire client (protocol v3 against the in-process emulator)
        c = _pg_client(pg_emulator)
    else:
        c = SQLiteStorageClient(
            StorageClientConfig(properties={"PATH": str(tmp_path / "pio.sqlite")})
        )
    yield c
    c.close()


@pytest.fixture(params=[
    "memory", "sqlite", "sqlite_file", "fileevents",
    "binevents", "binevents_py", "postgres",
])
def events_client(request, tmp_path, pg_emulator):
    """Event-store conformance adds the events-only fileevents and
    binevents backends (the reference ran the same LEventsSpec against
    hbase). binevents runs twice: native C++ scan path and the
    pure-Python codec fallback."""
    if request.param == "fileevents":
        from predictionio_tpu.storage.fileevents import FileEventsStorageClient

        c = FileEventsStorageClient(
            StorageClientConfig(properties={"PATH": str(tmp_path / "fe")})
        )
        yield c
        c.events().close()
        return
    if request.param.startswith("binevents"):
        from predictionio_tpu.storage.binevents import BinEventsStorageClient

        native = "true" if request.param == "binevents" else "false"
        c = BinEventsStorageClient(
            StorageClientConfig(
                properties={"PATH": str(tmp_path / "be"), "NATIVE": native}
            )
        )
        yield c
        c.events().close()
        return
    if request.param == "memory":
        c = MemoryStorageClient()
    elif request.param == "sqlite":
        c = SQLiteStorageClient(StorageClientConfig(test=True))
    elif request.param == "postgres":
        c = _pg_client(pg_emulator)
    else:
        c = SQLiteStorageClient(
            StorageClientConfig(properties={"PATH": str(tmp_path / "pio.sqlite")})
        )
    yield c
    c.close()


def ev(name="rate", entity="u1", minutes=0, target=None, props=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=entity,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=T0 + timedelta(minutes=minutes),
    )


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

class TestEvents:
    def test_insert_get_delete_roundtrip(self, events_client):
        events = events_client.events()
        events.init(1)
        e = ev(props={"rating": 4.5, "note": "good"}, target="i1")
        eid = events.insert(e, 1)
        got = events.get(eid, 1)
        assert got.event_id == eid
        assert got.properties.fields == {"rating": 4.5, "note": "good"}
        assert got.event_time == e.event_time
        assert got.target_entity_id == "i1"
        assert events.delete(eid, 1) is True
        assert events.delete(eid, 1) is False
        assert events.get(eid, 1) is None

    def test_channel_isolation(self, events_client):
        events = events_client.events()
        events.init(1)
        events.init(1, 5)
        eid = events.insert(ev(), 1, 5)
        assert events.get(eid, 1) is None
        assert events.get(eid, 1, 5) is not None
        assert list(events.find(1)) == []
        assert len(list(events.find(1, 5))) == 1

    def test_app_isolation(self, events_client):
        events = events_client.events()
        events.init(1)
        events.init(2)
        events.insert(ev(), 1)
        assert list(events.find(2)) == []

    def test_find_filters(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch(
            [
                ev("rate", "u1", 0, target="i1"),
                ev("rate", "u1", 10, target="i2"),
                ev("buy", "u1", 20, target="i2"),
                ev("rate", "u2", 30, target="i3"),
                ev("$set", "u1", 40, props={"a": 1}),
            ],
            1,
        )
        f = lambda **kw: list(events.find(1, None, EventFilter(**kw)))
        assert len(f()) == 5
        assert len(f(entity_id="u1")) == 4
        assert len(f(event_names=["rate"])) == 3
        assert len(f(event_names=["rate", "buy"])) == 4
        assert len(f(start_time=T0 + timedelta(minutes=10))) == 4
        assert len(f(until_time=T0 + timedelta(minutes=10))) == 1
        assert (
            len(f(start_time=T0 + timedelta(minutes=10), until_time=T0 + timedelta(minutes=30)))
            == 2
        )
        assert len(f(target_entity_id="i2")) == 2
        assert len(f(target_entity_type=None)) == 1  # only the $set
        assert len(f(entity_type="user")) == 5
        assert len(f(entity_type="other")) == 0

    def test_find_order_limit_reversed(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch([ev(minutes=m) for m in (30, 10, 20)], 1)
        times = [e.event_time for e in events.find(1)]
        assert times == sorted(times)
        newest = list(events.find(1, None, EventFilter(limit=1, reversed=True)))
        assert newest[0].event_time == T0 + timedelta(minutes=30)
        two = list(events.find(1, None, EventFilter(limit=2)))
        assert len(two) == 2

    def test_aggregate_properties(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch(
            [
                Event(
                    event="$set", entity_type="user", entity_id="u1",
                    properties=DataMap({"a": 1, "b": 2}), event_time=T0,
                ),
                Event(
                    event="$unset", entity_type="user", entity_id="u1",
                    properties=DataMap({"b": None}),
                    event_time=T0 + timedelta(minutes=1),
                ),
                Event(
                    event="$set", entity_type="user", entity_id="u2",
                    properties=DataMap({"c": 3}), event_time=T0,
                ),
                Event(
                    event="$delete", entity_type="user", entity_id="u2",
                    event_time=T0 + timedelta(minutes=1),
                ),
                Event(
                    event="$set", entity_type="item", entity_id="i1",
                    properties=DataMap({"x": 9}), event_time=T0,
                ),
            ],
            1,
        )
        out = events.aggregate_properties(1, "user")
        assert set(out) == {"u1"}
        assert out["u1"].fields == {"a": 1}
        # required-fields filter (LEvents.scala:246-252)
        assert events.aggregate_properties(1, "user", required=["missing"]) == {}

    def test_find_single_entity_latest(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch([ev("view", "u1", m, target=f"i{m}") for m in range(5)], 1)
        got = list(
            events.find_single_entity(1, "user", "u1", event_names=["view"], limit=2)
        )
        assert [e.target_entity_id for e in got] == ["i4", "i3"]

    def test_remove_drops_data(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert(ev(), 1)
        events.remove(1)
        assert list(events.find(1)) == []


# ---------------------------------------------------------------------------
# Columnar/row equivalence (PR 4: the columnar data plane)
# ---------------------------------------------------------------------------

def _columnar_seed_events():
    """A mixed batch exercising every column: targets present/absent,
    properties/tags/prId, equal timestamps (tie order must match the
    row path), sub-millisecond spacing, and multiple entity types."""
    out = [
        ev("rate", "u1", 0, target="i1", props={"rating": 4.5}),
        ev("buy", "u2", 1, target="i2"),
        ev("$set", "u1", 2, props={"a": 1, "nested": {"b": [1, 2]}}),
        ev("rate", "u3", 2, target="i3", props={"rating": 1.0}),  # tie @2min
        ev("view", "u1", 3, target="i9"),
        Event(event="note", entity_type="doc", entity_id="d1",
              properties=DataMap({"len": 7}), tags=("t1", "t2"),
              pr_id="pr-9", event_time=T0 + timedelta(minutes=4)),
        # sub-millisecond neighbors: ordering must agree with find()
        Event(event="view", entity_type="user", entity_id="u9",
              event_time=T0 + timedelta(minutes=5, microseconds=200)),
        Event(event="view", entity_type="user", entity_id="u9",
              event_time=T0 + timedelta(minutes=5, microseconds=900)),
    ]
    return out


_COLUMNAR_FILTERS = [
    EventFilter(),
    EventFilter(event_names=["rate", "buy"]),
    EventFilter(event_names=[]),                      # match nothing
    EventFilter(entity_type="user"),
    EventFilter(entity_type="user", entity_id="u1"),
    EventFilter(target_entity_type=None),             # target must be absent
    EventFilter(target_entity_type="item"),
    EventFilter(target_entity_id="i2"),
    EventFilter(start_time=T0 + timedelta(minutes=1),
                until_time=T0 + timedelta(minutes=4)),
    EventFilter(limit=3),
    EventFilter(limit=0),
    EventFilter(entity_type="user", entity_id="u1", reversed=True, limit=2),
    EventFilter(reversed=True),
]


def _assert_columnar_matches_rows(events_dao, app_id=1, batch_size=3):
    """For every filter: concatenated find_columnar batches materialize
    to EXACTLY the find() sequence (order, ties, limit cuts)."""
    for flt in _COLUMNAR_FILTERS:
        rows = list(events_dao.find(app_id, None, flt))
        got = []
        for batch in events_dao.find_columnar(app_id, None, flt,
                                              batch_size=batch_size):
            assert len(batch) <= batch_size
            assert len(batch.event_time_us) == len(batch.event_ids)
            got.extend(batch.to_events())
        assert got == rows, f"filter {flt} diverged"


class TestColumnarRowEquivalence:
    """find_columnar must round-trip to the exact event sequence find
    returns — for every backend, both the native fast paths and the
    generic rows->columns fallback (ISSUE 4 conformance gate)."""

    def test_native_path_matches_rows(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        _assert_columnar_matches_rows(events)

    def test_generic_fallback_matches_rows(self, events_client):
        """Force the base-class fallback (unbound call) even on backends
        that override find_columnar: the inherited path must stay
        correct for third-party backends that never override it."""
        from predictionio_tpu.storage import base as storage_base

        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        for flt in _COLUMNAR_FILTERS:
            rows = list(events.find(1, None, flt))
            got = [
                e
                for batch in storage_base.Events.find_columnar(
                    events, 1, None, flt, batch_size=2)
                for e in batch.to_events()
            ]
            assert got == rows, f"fallback filter {flt} diverged"

    def test_empty_table_yields_no_batches(self, events_client):
        events = events_client.events()
        events.init(1)
        assert list(events.find_columnar(1)) == []

    def test_batch_size_must_be_positive(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert(ev(), 1)
        with pytest.raises(ValueError):
            list(events.find_columnar(1, batch_size=0))

    def test_lazy_properties_decode_per_row(self, events_client):
        """The cold columns decode on demand and match the row path."""
        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        flt = EventFilter(event_names=["rate"])
        rows = list(events.find(1, None, flt))
        (batch,) = list(events.find_columnar(1, None, flt, batch_size=100))
        for i, e in enumerate(rows):
            assert batch.properties(i).fields == e.properties.fields
        # hot columns decode vectorized
        assert list(batch.entity_id.decode()) == [e.entity_id for e in rows]
        assert list(batch.event.decode()) == [e.event for e in rows]

    @pytest.mark.chaos
    def test_chaos_backend_columnar_conformance(self):
        """The chaos-wrapped DAO (fault injection + resilience above a
        memory inner store) must pass the same equivalence suite — the
        injected faults are absorbed by the retry layer and the batches
        still match the row path exactly."""
        from predictionio_tpu.storage.chaos import ChaosStorageClient

        inner = MemoryStorageClient()
        client = ChaosStorageClient.wrap(inner, fault_rate=0.3, seed=7)
        events = client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        _assert_columnar_matches_rows(events)


# ---------------------------------------------------------------------------
# Cursor-resume conformance (PR 14: the online tail follower's contract)
# ---------------------------------------------------------------------------

def _cursor_of(e, events_dao):
    """The (eventTime, id) cursor a consumer saves after row ``e``."""
    from predictionio_tpu.core.columns import datetime_to_us
    from predictionio_tpu.online.follower import TailCursor

    return TailCursor(datetime_to_us(e.event_time), e.event_id or "")


def _assert_exactly_once_resume(events_dao, flt=EventFilter(), app_id=1,
                                batch_size=2):
    """Cut the full find() sequence at EVERY position (so every batch
    boundary and every equal-timestamp tie is a cut point at
    batch_size=2) and pin that the resumed read yields exactly the
    remaining suffix — no skipped event, no duplicate."""
    from predictionio_tpu.online.follower import resume_columnar

    full = list(events_dao.find(app_id, None, flt))
    assert len(full) >= 6, "seed must exercise batch boundaries"
    for cut, row in enumerate(full):
        cursor = _cursor_of(row, events_dao)
        got = []
        for cols, idx in resume_columnar(events_dao, app_id, None, flt,
                                         cursor=cursor,
                                         batch_size=batch_size):
            sub = cols.to_events()
            got.extend(sub[int(i)] for i in idx)
        assert got == full[cut + 1:], (
            f"resume after row {cut} ({row.event_id}) diverged: "
            f"got {[e.event_id for e in got]}, want "
            f"{[e.event_id for e in full[cut + 1:]]}")


@pytest.mark.online
class TestColumnarCursorResume:
    """``find_columnar`` reads resumed from a saved ``(eventTime, id)``
    cursor must be exactly-once across batch boundaries on every
    backend — the online follower's correctness contract: a skipped
    event is a rating that never reaches the model, a duplicate breaks
    the exactly-once ordering PR 4 pinned (ISSUE 14 satellite)."""

    def test_resume_is_exactly_once_everywhere(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        _assert_exactly_once_resume(events)

    def test_resume_with_filter(self, events_client):
        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        _assert_exactly_once_resume(
            events, EventFilter(entity_type="user"))

    def test_resume_from_none_reads_everything(self, events_client):
        from predictionio_tpu.online.follower import resume_columnar

        events = events_client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        full = list(events.find(1))
        got = []
        for cols, idx in resume_columnar(events, 1, batch_size=3):
            sub = cols.to_events()
            got.extend(sub[int(i)] for i in idx)
        assert got == full

    def test_resume_rejects_limited_and_reversed_filters(
            self, events_client):
        from predictionio_tpu.online.follower import resume_columnar

        events = events_client.events()
        events.init(1)
        with pytest.raises(ValueError):
            list(resume_columnar(events, 1,
                                 filter=EventFilter(reversed=True)))
        with pytest.raises(ValueError):
            list(resume_columnar(events, 1, filter=EventFilter(limit=3)))

    def test_new_rows_behind_cursor_time_are_picked_up(
            self, events_client):
        """An event landing AFTER the cursor was saved but sorting
        inside the cursor's timestamp tie (greater id) must still be
        returned — the tie-resume half of the contract."""
        from predictionio_tpu.online.follower import resume_columnar

        events = events_client.events()
        events.init(1)
        ids = events.insert_batch(_columnar_seed_events(), 1)
        full = list(events.find(1))
        cursor = _cursor_of(full[-1], events)
        # same timestamp as the last row, id forced greater
        late = Event(event="view", entity_type="user", entity_id="u9",
                     event_time=full[-1].event_time,
                     event_id="z" * 32)
        assert "z" * 32 > max(i or "" for i in ids)
        events.insert(late, 1)
        got = []
        for cols, idx in resume_columnar(events, 1, cursor=cursor,
                                         batch_size=2):
            sub = cols.to_events()
            got.extend(sub[int(i)] for i in idx)
        assert [e.event_id for e in got] == ["z" * 32]

    @pytest.mark.chaos
    def test_chaos_backend_cursor_resume(self):
        """Same contract through the chaos-wrapped DAO: injected faults
        are absorbed by the retry layer and the resume stays
        exactly-once."""
        from predictionio_tpu.storage.chaos import ChaosStorageClient

        inner = MemoryStorageClient()
        client = ChaosStorageClient.wrap(inner, fault_rate=0.3, seed=7)
        events = client.events()
        events.init(1)
        events.insert_batch(_columnar_seed_events(), 1)
        _assert_exactly_once_resume(events)


# ---------------------------------------------------------------------------
# Metadata DAOs
# ---------------------------------------------------------------------------

class TestApps:
    def test_crud(self, client):
        apps = client.apps()
        app_id = apps.insert(App(0, "myapp", "desc"))
        assert app_id is not None and app_id > 0
        assert apps.get(app_id).name == "myapp"
        assert apps.get_by_name("myapp").id == app_id
        assert apps.insert(App(0, "myapp")) is None  # duplicate name
        apps.update(App(app_id, "renamed", None))
        assert apps.get_by_name("renamed") is not None
        id2 = apps.insert(App(0, "two"))
        assert id2 != app_id
        assert [a.id for a in apps.get_all()] == sorted([app_id, id2])
        apps.delete(app_id)
        assert apps.get(app_id) is None


class TestAccessKeys:
    def test_crud_and_generation(self, client):
        apps = client.apps()
        keys = client.access_keys()
        app_id = apps.insert(App(0, "a"))
        k = keys.insert(AccessKey("", app_id, ()))
        assert k and len(k) >= 32
        assert keys.get(k).appid == app_id
        k2 = keys.insert(AccessKey("explicit-key", app_id, ("rate", "buy")))
        assert k2 == "explicit-key"
        assert set(keys.get(k2).events) == {"rate", "buy"}
        assert keys.insert(AccessKey("explicit-key", app_id)) is None  # dup
        assert {a.key for a in keys.get_by_app_id(app_id)} == {k, k2}
        keys.update(AccessKey(k2, app_id, ("view",)))
        assert list(keys.get(k2).events) == ["view"]
        keys.delete(k)
        assert keys.get(k) is None


class TestChannels:
    @pytest.mark.skipif(
        not sqlite_supports_returning(),
        reason="container sqlite < 3.35 lacks RETURNING — the channels "
               "DAO (and the sqlite-backed PG emulator) cannot run here "
               "(container artifact, not a regression)")
    def test_crud_and_name_validation(self, client):
        channels = client.channels()
        cid = channels.insert(Channel(0, "ch-1", 7))
        assert cid > 0
        assert channels.get(cid).name == "ch-1"
        assert channels.insert(Channel(0, "bad name!", 7)) is None
        assert channels.insert(Channel(0, "x" * 17, 7)) is None
        cid2 = channels.insert(Channel(0, "ch-2", 7))
        assert {c.id for c in channels.get_by_app_id(7)} == {cid, cid2}
        channels.delete(cid)
        assert channels.get(cid) is None


def make_instance(status="INIT", start=T0, variant="v1"):
    return EngineInstance(
        id="",
        status=status,
        start_time=start,
        completion_time=start,
        engine_id="eng",
        engine_version="1",
        engine_variant=variant,
        engine_factory="my.Factory",
        env={"K": "v"},
        mesh_conf={"mesh": [2, 4]},
        algorithms_params='[{"name":"als"}]',
    )


class TestEngineInstances:
    def test_crud_and_latest_completed(self, client):
        insts = client.engine_instances()
        i1 = insts.insert(make_instance("COMPLETED", T0))
        i2 = insts.insert(make_instance("COMPLETED", T0 + timedelta(hours=1)))
        insts.insert(make_instance("INIT", T0 + timedelta(hours=2)))
        insts.insert(make_instance("COMPLETED", T0 + timedelta(hours=3), variant="v2"))
        got = insts.get(i1)
        assert got.env == {"K": "v"} and got.mesh_conf == {"mesh": [2, 4]}
        latest = insts.get_latest_completed("eng", "1", "v1")
        assert latest.id == i2
        assert len(insts.get_completed("eng", "1", "v1")) == 2
        import dataclasses

        insts.update(dataclasses.replace(got, status="FAILED"))
        assert insts.get(i1).status == "FAILED"
        insts.delete(i1)
        assert insts.get(i1) is None
        assert len(insts.get_all()) == 3

    def test_latest_completed_none(self, client):
        assert client.engine_instances().get_latest_completed("x", "y", "z") is None


class TestEvaluationInstances:
    def test_crud(self, client):
        insts = client.evaluation_instances()
        iid = insts.insert(
            EvaluationInstance(
                id="", status="INIT", start_time=T0, completion_time=T0,
                evaluation_class="my.Eval", evaluator_results="one-liner",
            )
        )
        got = insts.get(iid)
        assert got.evaluation_class == "my.Eval"
        import dataclasses

        insts.update(dataclasses.replace(got, status="EVALCOMPLETED"))
        assert [i.id for i in insts.get_completed()] == [iid]
        insts.delete(iid)
        assert insts.get(iid) is None


class TestModels:
    def test_roundtrip(self, client):
        models = client.models()
        blob = bytes(range(256)) * 10
        models.insert(Model("m1", blob))
        assert models.get("m1").models == blob
        models.insert(Model("m1", b"replaced"))
        assert models.get("m1").models == b"replaced"
        models.delete("m1")
        assert models.get("m1") is None
        assert models.get("never") is None


# ---------------------------------------------------------------------------
# Regression tests for review findings
# ---------------------------------------------------------------------------

class TestReviewRegressions:
    def test_naive_datetime_filter_consistent(self, client):
        """Naive filter bounds are interpreted as UTC on every backend."""
        events = client.events()
        events.init(1)
        events.insert(ev(minutes=0), 1)
        events.insert(ev(minutes=60), 1)
        naive = datetime(2020, 1, 1, 0, 30)  # no tzinfo
        got = list(events.find(1, None, EventFilter(start_time=naive)))
        assert len(got) == 1

    def test_insert_auto_inits_table(self, client):
        """insert without init() works identically on all backends."""
        events = client.events()
        eid = events.insert(ev(), 42)
        assert events.get(eid, 42) is not None
        ids = events.insert_batch([ev(minutes=1), ev(minutes=2)], 43)
        assert len(list(events.find(43))) == 2
        assert len(ids) == 2

    def test_channel_duplicate_id_returns_none(self, client):
        channels = client.channels()
        assert channels.insert(Channel(5, "a", 1)) == 5
        assert channels.insert(Channel(5, "b", 1)) is None


def test_register_backend_keeps_builtins(tmp_path):
    """Registering a plugin backend must not disable builtins."""
    from predictionio_tpu.storage import register_backend
    from predictionio_tpu.storage.memory import MemoryStorageClient
    from predictionio_tpu.storage.registry import Storage

    register_backend("custom-test", MemoryStorageClient)
    env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }
    Storage(env).verify_all_data_objects()


def test_engine_instance_mixed_offset_ordering(client):
    """Latest-completed must compare instants, not offset strings."""
    from datetime import timedelta, timezone as tz

    insts = client.engine_instances()
    # A at 12:00Z; B at 23:00+14:00 == 09:00Z (earlier instant, later string)
    a = insts.insert(make_instance("COMPLETED", T0.replace(hour=12)))
    insts.insert(
        make_instance(
            "COMPLETED",
            T0.replace(hour=23, tzinfo=tz(timedelta(hours=14))),
        )
    )
    assert insts.get_latest_completed("eng", "1", "v1").id == a


def test_fileevents_persists_across_reopen(tmp_path):
    """The append-only log replays after a restart (the durability HBase
    gave the reference's event store)."""
    from predictionio_tpu.storage.fileevents import FileEventsStorageClient

    path = str(tmp_path / "fe")
    c1 = FileEventsStorageClient(StorageClientConfig(properties={"PATH": path}))
    events = c1.events()
    events.init(1)
    kept = events.insert(ev(props={"rating": 2.0}), 1)
    dropped = events.insert(ev(entity="u2"), 1)
    events.delete(dropped, 1)

    c2 = FileEventsStorageClient(StorageClientConfig(properties={"PATH": path}))
    replayed = list(c2.events().find(1, filter=EventFilter()))
    assert [e.event_id for e in replayed] == [kept]
    assert replayed[0].properties["rating"] == 2.0
