"""Static policy check: no remote-backend network call may bypass the
resilience layer.

Walks the AST of every remote-backend module and asserts that each raw
network call site (``urlopen`` / ``socket.create_connection``) sits
inside that module's designated guarded function, and that the guarded
function is invoked ONLY through ``resilient(...)`` (or, for the pgwire
socket, only from the pool's resilient-wrapped connect). A new
``urlopen`` dropped into a DAO method, or a direct call to a guarded
raw function, fails here before it ever flakes in production."""

from __future__ import annotations

import ast
import os

import predictionio_tpu.storage as storage_pkg

STORAGE_DIR = os.path.dirname(storage_pkg.__file__)

#: raw-network callables we police
NET_CALLS = {"urlopen", "create_connection"}

#: module -> set of function (qual)names allowed to contain raw network
#: calls; everything else in the module must be network-free
GUARDED_NET_SITES = {
    "elasticsearch.py": {"ESClient._raw_request"},
    "s3.py": {"S3Models._raw_request"},
    "pgwire.py": {"_open_socket"},
    "postgres.py": set(),
    "hdfs.py": set(),
}

#: module -> functions that may ONLY be referenced (outside their own
#: definition) on lines that route through resilient(...)
RESILIENT_ONLY_REFS = {
    "elasticsearch.py": {"_raw_request"},
    "s3.py": {"_raw_request"},
    "postgres.py": {"_open_connection"},
    "hdfs.py": {"_write", "_read", "_remove"},
}


def _load(module_file: str) -> tuple[str, ast.Module]:
    path = os.path.join(STORAGE_DIR, module_file)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return src, ast.parse(src, filename=path)


def _net_call_sites(tree: ast.Module) -> dict[str, set[str]]:
    """Map qualified enclosing-function name -> net-call names found."""
    sites: dict[str, set[str]] = {}

    def visit(node: ast.AST, stack: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            stack = stack + (node.name,)
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name in NET_CALLS:
                qual = ".".join(stack) or "<module>"
                sites.setdefault(qual, set()).add(name)
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(tree, ())
    return sites


class TestNoPolicyBypassingNetworkCalls:
    def test_net_calls_only_in_guarded_functions(self):
        for module_file, allowed in GUARDED_NET_SITES.items():
            _, tree = _load(module_file)
            sites = _net_call_sites(tree)
            stray = {q: c for q, c in sites.items() if q not in allowed}
            assert not stray, (
                f"{module_file}: raw network calls outside the guarded "
                f"functions {sorted(allowed)}: {stray} — route them "
                f"through resilient()")
            # the guard list must not go stale: every allowed site exists
            if allowed:
                assert set(sites) == allowed, (
                    f"{module_file}: expected guarded network sites "
                    f"{sorted(allowed)}, found {sorted(sites)}")

    def test_guarded_functions_called_only_via_resilient(self):
        """Every reference to a guarded raw function (outside its own
        ``def``) must appear as an argument of a ``resilient(...)``
        call — no direct invocation, no aliasing it out."""
        for module_file, guarded in RESILIENT_ONLY_REFS.items():
            _, tree = _load(module_file)
            # node -> parent map for ancestry walks
            parents: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node

            def inside_resilient(node: ast.AST) -> bool:
                cur = node
                while cur in parents:
                    cur = parents[cur]
                    if (isinstance(cur, ast.Call)
                            and isinstance(cur.func, ast.Name)
                            and cur.func.id == "resilient"):
                        return True
                return False

            for name in guarded:
                refs = [
                    node for node in ast.walk(tree)
                    if (isinstance(node, ast.Attribute) and node.attr == name)
                    or (isinstance(node, ast.Name) and node.id == name)
                ]
                assert refs, (
                    f"{module_file}: guarded function {name} is never "
                    f"referenced — stale guard list")
                bypass = [
                    f"{module_file}:{n.lineno}" for n in refs
                    if not inside_resilient(n)
                ]
                assert not bypass, (
                    f"{module_file}: {name} referenced outside "
                    f"resilient(...): {bypass}")

    def test_pgwire_socket_guard_routes_through_pool(self):
        """pgwire's _open_socket is reachable only from PGConnection
        construction, and package code constructs PGConnection only
        inside postgres._PGPool._open_connection — which the check above
        proves is resilient()-routed."""
        src, tree = _load("pgwire.py")
        refs = [line.strip() for line in src.splitlines()
                if "_open_socket(" in line and "def _open_socket(" not in line]
        assert refs == ["self._sock = _open_socket(host, port, timeout)"], refs

        pg_src, pg_tree = _load("postgres.py")
        ctor_lines = {
            node.lineno
            for node in ast.walk(pg_tree)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "PGConnection"
        }
        assert ctor_lines, "postgres.py no longer constructs PGConnection?"
        spans = {
            (node.lineno, max(getattr(node, "end_lineno", node.lineno),
                              node.lineno))
            for node in ast.walk(pg_tree)
            if isinstance(node, ast.FunctionDef)
            and node.name == "_open_connection"
        }
        assert spans, "postgres.py lost _PGPool._open_connection"
        for line in ctor_lines:
            assert any(lo <= line <= hi for lo, hi in spans), (
                f"postgres.py:{line}: PGConnection constructed outside "
                f"_open_connection — bypasses the connect resilience")

    def test_every_remote_backend_imports_resilience(self):
        for module_file in GUARDED_NET_SITES:
            src, _ = _load(module_file)
            if module_file == "pgwire.py":
                continue  # guarded one level up, in postgres.py
            assert "predictionio_tpu.utils.resilience" in src, (
                f"{module_file} does not import the resilience layer")
