"""Static policy check: no remote-backend network call may bypass the
resilience layer.

PR 1 proved this check's shape with a one-off AST walker; the walker
now lives in the ``resilience-bypass`` lint rule
(predictionio_tpu/analysis/rules/resilience.py) with its guard tables
in ``analysis.config.default_config()``, and this file is the thin
wrapper that keeps the original test name/intent: a new ``urlopen``
dropped into a DAO method, a direct call to a guarded raw function, or
a ``PGConnection`` constructed outside the pool's resilient-wrapped
connect all fail here before they ever flake in production.
"""

from __future__ import annotations

import os

import pytest

import predictionio_tpu.storage as storage_pkg
from predictionio_tpu.analysis import default_config, format_findings, lint_package

pytestmark = pytest.mark.lint


class TestNoPolicyBypassingNetworkCalls:
    def test_storage_package_clean(self):
        """The resilience-bypass rule over the real storage backends:
        guarded net sites, resilient-only references, the pgwire
        constructor guard, import checks, and stale-guard detection all
        run; zero findings expected."""
        findings = lint_package(rule_ids=["resilience-bypass"])
        assert not findings, "\n" + format_findings(findings)

    def test_guard_tables_cover_every_remote_backend(self):
        """The policy must keep policing the modules that make network
        calls — an empty/renamed guard table would pass trivially."""
        options = default_config().rules["resilience-bypass"].options
        guarded = options["guarded_sites"]
        for module_file in ("elasticsearch.py", "s3.py", "pgwire.py",
                            "postgres.py", "hdfs.py"):
            assert module_file in guarded, (
                f"{module_file} dropped from the resilience guard table")
            assert os.path.exists(os.path.join(
                os.path.dirname(storage_pkg.__file__), module_file))
        # the ctor guard that routes pgwire sockets through the pool,
        # and the call guard pinning _open_socket to PGConnection.__init__
        assert options["ctor_guard"]["postgres.py"] == {
            "PGConnection": "_open_connection"}
        assert options["call_guard"]["pgwire.py"] == {
            "_open_socket": ["PGConnection.__init__"]}
