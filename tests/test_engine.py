"""Engine/DASE pipeline semantics tests.

Modeled on the reference's EngineTest/EngineWorkflowTest
(core/src/test/scala/.../controller/EngineTest.scala, workflow/
EngineWorkflowTrainTest etc.) driven by the SampleEngine fake.
"""

import dataclasses

import pytest

from predictionio_tpu.controller import (
    EmptyParams,
    EngineParams,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    params_from_json,
)
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams

from tests.sample_engine import (
    AlgoParams,
    DSParams,
    Prediction,
    Query,
    SampleAlgorithm,
    TrainingData,
    default_params,
    make_engine,
)


@pytest.fixture
def ctx():
    return EngineContext(workflow_params=WorkflowParams())


def test_train_runs_pipeline(ctx):
    engine = make_engine()
    result = engine.train(ctx, default_params(n_algos=2))
    assert len(result.models) == 2
    assert result.models[0].source_id == 7  # datasource id flowed through prepare
    assert result.models[0].mult == 1 and result.models[1].mult == 2
    assert result.persisted == result.models  # auto persistence


def test_train_multiple_same_algo_different_params(ctx):
    engine = make_engine()
    ep = EngineParams.of(
        data_source=DSParams(id=1),
        algorithms=[("sample", AlgoParams(id=0, mult=3)), ("sample", AlgoParams(id=1, mult=5))],
    )
    result = engine.train(ctx, ep)
    assert [m.mult for m in result.models] == [3, 5]


def test_sanity_check_fails_training(ctx):
    engine = make_engine()

    class BadDS(type(engine.make_components(default_params())[0])):
        def read_training(self, ctx):
            return TrainingData(id=0, bad=True)

    engine.data_source_class_map[""] = BadDS
    with pytest.raises(ValueError, match="sanity check"):
        engine.train(ctx, default_params())


def test_sanity_check_skipped(ctx):
    engine = make_engine()

    class BadDS(type(engine.make_components(default_params())[0])):
        def read_training(self, ctx):
            return TrainingData(id=0, bad=True)

    engine.data_source_class_map[""] = BadDS
    ctx2 = EngineContext(workflow_params=WorkflowParams(skip_sanity_check=True))
    result = engine.train(ctx2, default_params())
    assert len(result.models) == 2


def test_stop_after_read_and_prepare():
    engine = make_engine()
    with pytest.raises(StopAfterReadInterruption):
        engine.train(
            EngineContext(WorkflowParams(stop_after_read=True)), default_params()
        )
    with pytest.raises(StopAfterPrepareInterruption):
        engine.train(
            EngineContext(WorkflowParams(stop_after_prepare=True)), default_params()
        )


def test_eval_aligns_multi_algo_predictions(ctx):
    engine = make_engine()
    results = engine.eval(ctx, default_params(n_algos=2))
    assert len(results) == 2  # n_folds
    ei, fold = results[0]
    assert ei == {"fold": 0}
    assert len(fold) == 3
    for q, p, a in fold:
        # serving sums algo predictions: x*1 + x*2
        assert p.value == q.x * 3
        assert p.tags == ("algo0", "algo1", "served")
        assert a == q.x * 10


def test_unknown_component_name(ctx):
    engine = make_engine()
    ep = EngineParams.of(algorithms=[("nope", EmptyParams())])
    with pytest.raises(ValueError, match="nope"):
        engine.train(ctx, ep)


def test_params_from_json_binding():
    p = params_from_json(DSParams, {"id": 3, "n_train": 10})
    assert p == DSParams(id=3, n_train=10)
    with pytest.raises(ValueError, match="typo_field"):
        params_from_json(DSParams, {"typo_field": 1})
    assert params_from_json(DSParams, None) == DSParams()


def test_params_from_json_camel_case():
    """Reference engine.json files use camelCase keys (numIterations,
    lambda, appName) — they must bind to the snake_case fields."""
    from predictionio_tpu.templates.recommendation import ALSAlgorithmParams

    p = params_from_json(
        ALSAlgorithmParams,
        {"rank": 5, "numIterations": 7, "lambda": 0.25, "implicitPrefs": True},
    )
    assert (p.rank, p.num_iterations, p.lambda_, p.implicit_prefs) == (
        5, 7, 0.25, True,
    )
    # camelCase typos still rejected
    with pytest.raises(ValueError, match="num_iteratons"):
        params_from_json(ALSAlgorithmParams, {"numIteratons": 3})
    # both spellings of one field at once is ambiguous
    with pytest.raises(ValueError, match="Duplicate"):
        params_from_json(
            ALSAlgorithmParams, {"numIterations": 3, "num_iterations": 4}
        )


def test_variant_json_to_engine_params(ctx):
    engine = make_engine()
    variant = {
        "id": "sample-variant",
        "engineFactory": "tests.sample_engine.engine_factory",
        "datasource": {"params": {"id": 9, "n_train": 3}},
        "algorithms": [
            {"name": "sample", "params": {"id": 0, "mult": 4}},
            {"name": "unpersisted", "params": {"id": 1}},
        ],
    }
    ep = engine.params_from_variant_json(variant)
    assert ep.data_source_params[1] == DSParams(id=9, n_train=3)
    assert ep.algorithm_params_list[0] == ("sample", AlgoParams(id=0, mult=4))
    result = engine.train(ctx, ep)
    assert result.models[0].mult == 4
    assert result.persisted[1] is None  # unpersisted algo


def test_instance_params_roundtrip(ctx):
    """EngineParams -> stored JSON blobs -> EngineParams (deploy path)."""
    import json

    from predictionio_tpu.controller.params import params_to_json

    engine = make_engine()
    ep = default_params()
    ds_json = json.dumps(
        {"name": ep.data_source_params[0], "params": params_to_json(ep.data_source_params[1])}
    )
    prep_json = json.dumps({"name": "", "params": {}})
    algos_json = json.dumps(
        [{"name": n, "params": params_to_json(p)} for n, p in ep.algorithm_params_list]
    )
    serving_json = json.dumps({"name": "", "params": {}})
    ep2 = engine.params_from_instance_json(ds_json, prep_json, algos_json, serving_json)
    assert ep2.data_source_params == ep.data_source_params
    assert ep2.algorithm_params_list == ep.algorithm_params_list


def test_prepare_deploy_with_retrain(ctx):
    engine = make_engine()
    ep = EngineParams.of(
        data_source=DSParams(id=2),
        algorithms=[("sample", AlgoParams(id=0, mult=2)), ("unpersisted", AlgoParams(id=1, mult=9))],
    )
    result = engine.train(ctx, ep)
    assert result.persisted[0] is not None and result.persisted[1] is None
    models = engine.prepare_deploy(ctx, ep, result.persisted)
    assert models[0].mult == 2
    assert models[1].mult == 9  # retrained on deploy
    p = SampleAlgorithm(AlgoParams(id=1, mult=9)).predict(models[1], Query(x=3))
    assert p == Prediction(value=27, tags=("algo1",))


def test_retrain_on_deploy_trains_the_serving_instances():
    """Regression for the round-3 deploy-path state bug, retrain
    branch: when models were not persisted, prepare_deploy must retrain
    on the SAME algorithm instances that will serve — train hooks stash
    serve-time state on the instance exactly like load_model hooks
    (ecommerce's live-constraint context), and training throwaway
    instances silently drops it."""
    # sentinel storage: identity below proves the parent context's
    # storage propagated (both resolving the Storage.default()
    # singleton would pass vacuously)
    sentinel = object()
    ctx = EngineContext(workflow_params=WorkflowParams(), storage=sentinel)
    engine = make_engine()
    ep = EngineParams.of(
        data_source=DSParams(id=2),
        algorithms=[("unpersisted", AlgoParams(id=1, mult=9))],
    )
    result = engine.train(ctx, ep)
    assert result.persisted[0] is None

    _, _, serving_algos, _ = engine.make_components(ep)
    assert serving_algos[0]._trained_with is None
    models = engine.prepare_deploy(ctx, ep, result.persisted,
                                   algorithms=serving_algos)
    assert models[0].mult == 9
    # the serving instance itself ran train(): its stash is populated
    # (with the save_model=False derived context prepare_deploy uses)
    trained_ctx = serving_algos[0]._trained_with
    assert trained_ctx is not None
    assert trained_ctx.storage is sentinel
    assert trained_ctx.workflow_params.save_model is False
