"""Resilience layer unit tests: RetryPolicy, CircuitBreaker (with the
injectable clock — transitions asserted deterministically, no wall-time
sleeps), the resilient() wrapper, deadlines, and metrics exposure."""

from __future__ import annotations

import random

import pytest

from predictionio_tpu.utils.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    ManualClock,
    Resilience,
    RetryPolicy,
    StorageUnavailableError,
    TransientError,
    deadline_scope,
    registry_snapshot,
    remaining_deadline,
    resilient,
    retry_after_hint,
)


class TestRetryPolicy:
    def test_full_jitter_bounds_and_growth(self):
        p = RetryPolicy(base_delay=0.1, max_delay=2.0, multiplier=2.0)
        rng = random.Random(0)
        for i in range(6):
            cap = min(2.0, 0.1 * 2 ** i)
            for _ in range(50):
                d = p.backoff(i, rng)
                assert 0.0 <= d <= cap

    def test_jitter_floor_guarantees_minimum_wait(self):
        p = RetryPolicy(base_delay=1.0, max_delay=2.0, jitter_floor=0.5)
        rng = random.Random(0)
        for i in range(4):
            cap = min(2.0, 1.0 * 2 ** i)
            for _ in range(50):
                d = p.backoff(i, rng)
                assert cap / 2 <= d <= cap   # equal jitter, never ~0

    def test_no_jitter_is_deterministic_cap(self):
        p = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=False)
        rng = random.Random(0)
        assert p.backoff(0, rng) == pytest.approx(0.1)
        assert p.backoff(1, rng) == pytest.approx(0.2)
        assert p.backoff(5, rng) == pytest.approx(1.0)  # capped

    def test_from_properties(self):
        p = RetryPolicy.from_properties({
            "RETRY_MAX_ATTEMPTS": "7",
            "RETRY_BASE_DELAY_MS": "10",
            "RETRY_MAX_DELAY_MS": "500",
            "RETRY_JITTER": "false",
            "RETRY_DEADLINE_MS": "2500",
        })
        assert p.max_attempts == 7
        assert p.base_delay == pytest.approx(0.01)
        assert p.max_delay == pytest.approx(0.5)
        assert p.jitter is False
        assert p.deadline == pytest.approx(2.5)

    def test_from_properties_env_fallback(self, monkeypatch):
        monkeypatch.setenv("PIO_RESILIENCE_RETRY_MAX_ATTEMPTS", "9")
        p = RetryPolicy.from_properties({})
        assert p.max_attempts == 9
        # explicit property beats env
        p = RetryPolicy.from_properties({"RETRY_MAX_ATTEMPTS": "2"})
        assert p.max_attempts == 2


class TestCircuitBreaker:
    """The acceptance transition chain, on a manual clock: closed →
    open → half-open → closed, each edge asserted deterministically."""

    def test_transition_chain(self):
        clock = ManualClock()
        b = CircuitBreaker("t", failure_threshold=3, reset_timeout=30.0,
                           clock=clock)
        assert b.state == "closed"
        for _ in range(2):
            b.before_call()
            b.record_failure()
        assert b.state == "closed"           # below threshold
        b.before_call()
        b.record_failure()                   # third consecutive failure
        assert b.state == "open"
        assert b.opens == 1

        with pytest.raises(CircuitOpenError) as e:
            b.before_call()                  # short-circuits while open
        assert e.value.retry_after == pytest.approx(30.0)

        clock.advance(29.9)
        with pytest.raises(CircuitOpenError):
            b.before_call()                  # still open just before reset
        clock.advance(0.2)
        assert b.state == "half_open"
        b.before_call()                      # the probe is admitted
        with pytest.raises(CircuitOpenError):
            b.before_call()                  # ... but only one at a time
        b.record_success()
        assert b.state == "closed"

    def test_half_open_failure_reopens(self):
        clock = ManualClock()
        b = CircuitBreaker("t", failure_threshold=1, reset_timeout=10.0,
                           clock=clock)
        b.before_call()
        b.record_failure()
        assert b.state == "open"
        clock.advance(10.0)
        b.before_call()                      # probe
        b.record_failure()                   # probe fails -> re-open
        assert b.state == "open"
        assert b.opens == 2
        with pytest.raises(CircuitOpenError) as e:
            b.before_call()
        assert e.value.retry_after == pytest.approx(10.0)

    def test_success_resets_failure_streak(self):
        b = CircuitBreaker("t", failure_threshold=2, clock=ManualClock())
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == "closed"           # streak broken, not cumulative

    def test_from_properties_disabled(self):
        assert CircuitBreaker.from_properties(
            "x", {"BREAKER_THRESHOLD": "0"}) is None
        b = CircuitBreaker.from_properties(
            "x", {"BREAKER_THRESHOLD": "2", "BREAKER_RESET_S": "5"})
        assert b.failure_threshold == 2
        assert b.reset_timeout == pytest.approx(5.0)


def _flaky(failures: int, exc=TransientError):
    """A callable failing the first ``failures`` times."""
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] <= failures:
            raise exc(f"boom {state['n']}")
        return state["n"]

    return fn


def _resilience(**kw) -> Resilience:
    kw.setdefault("clock", ManualClock())
    kw.setdefault("register", False)
    kw.setdefault("policy", RetryPolicy(max_attempts=4, base_delay=0.01,
                                        jitter=False))
    return Resilience("test", **kw)


class TestResilientCall:
    def test_retries_then_succeeds(self):
        r = _resilience()
        assert resilient(r, _flaky(2)) == 3
        snap = r.snapshot()
        assert snap["calls"] == 1
        assert snap["attempts"] == 3
        assert snap["retries"] == 2
        assert snap["failures"] == 2
        assert snap["unavailable"] == 0

    def test_exhaustion_wraps_in_storage_unavailable(self):
        r = _resilience()
        with pytest.raises(StorageUnavailableError) as e:
            resilient(r, _flaky(10))
        assert isinstance(e.value.__cause__, TransientError)
        assert r.snapshot()["unavailable"] == 1
        assert e.value.retry_after > 0

    def test_non_retryable_passes_through_untouched(self):
        r = _resilience()
        with pytest.raises(KeyError):
            resilient(r, _flaky(1, exc=KeyError))
        assert r.snapshot()["retries"] == 0

    def test_breaker_short_circuits_after_open(self):
        clock = ManualClock()
        calls = {"n": 0}

        def always_down():
            calls["n"] += 1
            raise ConnectionError("refused")

        r = _resilience(
            clock=clock,
            policy=RetryPolicy(max_attempts=2, base_delay=0.01, jitter=False),
            breaker=CircuitBreaker("test", failure_threshold=2,
                                   reset_timeout=60.0, clock=clock),
        )
        with pytest.raises(StorageUnavailableError):
            resilient(r, always_down)        # 2 attempts, breaker opens
        assert r.breaker.state == "open"
        before = calls["n"]
        with pytest.raises(StorageUnavailableError) as e:
            resilient(r, always_down)        # short-circuited: no attempt
        assert calls["n"] == before
        assert e.value.retry_after == pytest.approx(60.0)
        assert r.snapshot()["short_circuits"] == 1

        # recovery: reset elapses, the half-open probe succeeds, closed
        clock.advance(60.0)
        assert resilient(r, lambda: "up") == "up"
        assert r.breaker.state == "closed"

    def test_policy_deadline_stops_retries(self):
        clock = ManualClock()
        r = _resilience(
            clock=clock,
            policy=RetryPolicy(max_attempts=100, base_delay=1.0,
                               jitter=False, deadline=2.5),
        )
        with pytest.raises(StorageUnavailableError):
            resilient(r, _flaky(100))
        # 1s + 2s sleeps fit a 2.5s budget only once: attempts 1,2,(3rd
        # blocked: 1+2=3 >= 2.5 after two sleeps) — assert bounded work
        assert r.snapshot()["attempts"] <= 3

    def test_ambient_deadline_scope(self):
        r = _resilience(policy=RetryPolicy(max_attempts=50, base_delay=10.0,
                                           jitter=False))
        with deadline_scope(0.05):
            assert remaining_deadline() <= 0.05
            with pytest.raises(StorageUnavailableError):
                resilient(r, _flaky(50))
        assert remaining_deadline() is None
        # a 10s delay never fits a 50ms budget: exactly one attempt
        assert r.snapshot()["attempts"] == 1

    def test_nested_deadline_only_shrinks(self):
        with deadline_scope(10.0):
            with deadline_scope(60.0):
                assert remaining_deadline() <= 10.0


class TestReviewRegressions:
    def test_non_retryable_during_half_open_releases_probe(self):
        """A 4xx/auth error during the half-open probe means the backend
        RESPONDED: the probe slot must be released (and the breaker
        closed), not wedged open forever."""
        clock = ManualClock()
        r = _resilience(
            clock=clock,
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker("t", failure_threshold=1,
                                   reset_timeout=10.0, clock=clock),
        )
        with pytest.raises(StorageUnavailableError):
            resilient(r, _flaky(99))             # opens the breaker
        clock.advance(10.0)
        with pytest.raises(KeyError):            # half-open probe: app error
            resilient(r, _flaky(99, exc=KeyError))
        assert r.breaker.state == "closed"       # NOT wedged half-open
        assert resilient(r, lambda: "up") == "up"

    def test_interrupt_during_half_open_probe_releases_slot(self):
        """A KeyboardInterrupt mid-probe must not move the breaker OR
        leak the probe slot — a process that survives the interrupt
        must still be able to probe the backend."""
        clock = ManualClock()
        r = _resilience(
            clock=clock,
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker("t", failure_threshold=1,
                                   reset_timeout=10.0, clock=clock),
        )
        with pytest.raises(StorageUnavailableError):
            resilient(r, _flaky(99))             # opens the breaker
        clock.advance(10.0)

        def interrupted():
            raise KeyboardInterrupt

        with pytest.raises(KeyboardInterrupt):
            resilient(r, interrupted)            # probe interrupted
        assert r.breaker.state == "half_open"    # not closed, not wedged
        assert resilient(r, lambda: "up") == "up"  # next probe admitted
        assert r.breaker.state == "closed"

    def test_nested_unavailable_is_terminal_not_retried(self):
        """chaos-over-remote stacking: an inner policy's exhausted
        StorageUnavailableError must pass through the outer layer with
        ONE attempt (no retry multiplication during an outage), while
        still counting against the outer breaker."""
        clock = ManualClock()
        r = _resilience(
            clock=clock,
            policy=RetryPolicy(max_attempts=12, base_delay=0.01),
            breaker=CircuitBreaker("outer", failure_threshold=2,
                                   clock=clock),
        )
        inner_error = StorageUnavailableError("inner", "down", 5.0)

        def exhausted():
            raise inner_error

        for _ in range(2):
            with pytest.raises(StorageUnavailableError) as e:
                resilient(r, exhausted)
            assert e.value is inner_error        # untouched, retry_after kept
        assert r.snapshot()["attempts"] == 2     # one per call, no retries
        assert r.breaker.state == "open"         # outage still counted

    def test_batcher_propagates_deadline_to_dispatcher_thread(self):
        """deadline_scope is a contextvar and does not cross threads on
        its own; QueryBatcher.submit must carry the remaining budget
        into the dispatcher so storage retries under a batch dispatch
        see it."""
        from predictionio_tpu.workflow.deploy import QueryBatcher

        seen: list = []

        class Deployed:
            def query_batch(self, qs):
                seen.append(remaining_deadline())
                return [q for q in qs]

        batcher = QueryBatcher(lambda: Deployed(), batch_wait_ms=0.0)
        try:
            with deadline_scope(5.0):
                assert batcher.submit("q") == "q"
            assert batcher.submit("r") == "r"    # no ambient deadline
        finally:
            batcher.close()
        assert seen[0] is not None and 0 < seen[0] <= 5.0
        assert seen[1] is None


class TestMetricsExposure:
    def test_registry_snapshot_via_stats(self):
        from predictionio_tpu.api.stats import resilience_snapshot

        r = Resilience("unit-test/registered",
                       policy=RetryPolicy(max_attempts=1))
        r.call(lambda: 1)
        snap = resilience_snapshot()
        assert snap == registry_snapshot()
        assert snap["unit-test/registered"]["calls"] >= 1

    def test_breaker_state_in_snapshot(self):
        clock = ManualClock()
        r = _resilience(
            clock=clock,
            breaker=CircuitBreaker("b", failure_threshold=1, clock=clock))
        with pytest.raises(StorageUnavailableError):
            resilient(r, _flaky(99))
        snap = r.snapshot()
        assert snap["breaker"]["state"] == "open"
        assert snap["breaker"]["opens"] == 1


class TestRecordFallback:
    def test_counter_visible_in_registry(self):
        from predictionio_tpu.utils.resilience import record_fallback

        record_fallback("unit-test/fallbacks")
        record_fallback("unit-test/fallbacks")
        assert registry_snapshot()["unit-test/fallbacks"]["fallbacks"] == 2


class TestRetryAfterHint:
    def test_hint_from_exception(self):
        assert retry_after_hint(StorageUnavailableError("x", "m", 7.5)) == 7.5
        assert retry_after_hint(ValueError("x")) == 1.0
        assert retry_after_hint(ValueError("x"), default=3.0) == 3.0


class TestServerConfigDeadline:
    def test_request_deadline_field_defaults_off(self):
        from predictionio_tpu.workflow.deploy import ServerConfig

        assert ServerConfig().request_deadline_ms == 0.0

    def test_bind_backoff_is_jittered_policy(self):
        """The engine server's bind retry now draws from RetryPolicy
        full jitter instead of a fixed 1s sleep."""
        from predictionio_tpu.api.http_base import RestServer

        policy = RestServer.bind_backoff
        assert isinstance(policy, RetryPolicy)
        assert policy.jitter is True
        rng = random.Random(1)
        delays = [policy.backoff(0, rng) for _ in range(8)]
        assert len({round(d, 6) for d in delays}) > 1   # actually jittered
        # ...but floored: a stopping predecessor gets a real wait window
        assert all(d >= policy.base_delay * policy.jitter_floor
                   for d in delays)
        assert policy.jitter_floor > 0
