"""Chaos end-to-end pins for the durable-ingest WAL (ISSUE 13): a hard
storage outage under live HTTP ingest loses ZERO events and serves
ZERO 5xx while under the journal's disk budget; post-drain storage
contents exactly equal the no-outage run (order and acknowledged ids);
and a ``kill -9`` of the event server mid-journal recovers by
truncating the torn tail and replaying every acknowledged record.

Ride-through semantics proven here, WAL internals in tests/test_wal.py,
batch per-event statuses in tests/test_event_server.py."""

import datetime
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.storage.base import AccessKey, App, EventFilter
from predictionio_tpu.storage.registry import Storage

pytestmark = [pytest.mark.wal, pytest.mark.chaos]

SEED = 20260804


def chaos_storage(fault_rate: str = "0.0") -> Storage:
    """All three repositories on a chaos-wrapped MEMORY backend with a
    tight retry budget (outage flips must surface fast, not after 12
    invisible retries)."""
    return Storage({
        "PIO_STORAGE_SOURCES_C_TYPE": "chaos",
        "PIO_STORAGE_SOURCES_C_TARGET": "memory",
        "PIO_STORAGE_SOURCES_C_FAULT_RATE": fault_rate,
        "PIO_STORAGE_SOURCES_C_SEED": str(SEED),
        "PIO_STORAGE_SOURCES_C_RETRY_MAX_ATTEMPTS": "2",
        "PIO_STORAGE_SOURCES_C_RETRY_BASE_DELAY_MS": "1",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "C",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "C",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "C",
    })


def post_json(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def event_payload(client: int, j: int) -> dict:
    t = (datetime.datetime(2024, 3, 1, tzinfo=datetime.timezone.utc)
         + datetime.timedelta(seconds=j, milliseconds=client))
    return {
        "event": "rate", "entityType": "user",
        "entityId": f"c{client}-u{j}",
        "targetEntityType": "item", "targetEntityId": f"i{j % 7}",
        "properties": {"rating": j % 5},
        # explicit eventTime: the no-outage and outage runs must store
        # IDENTICAL sequences, so nothing may default to arrival time
        "eventTime": t.strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z",
    }


def stored_sequence(storage: Storage, app_id: int):
    """The find() ordering contract: (eventTime, then the backend's id
    tiebreak). Compared between runs on the time-ordered payload keys."""
    return [
        (e.event, e.entity_id, e.target_entity_id, e.event_time,
         e.properties.to_json())
        for e in storage.get_events().find(app_id, None, EventFilter())
    ]


def wait_until(predicate, timeout=20.0, interval=0.05):
    """Deadline-poll (never assert the first read — the drainer races
    the HTTP response on a small host)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TestOutageRideThrough:
    def test_hard_outage_zero_loss_zero_5xx_exact_contents(self, tmp_path):
        """THE headline chaos pin: T seconds of total backend outage
        under live multi-threaded HTTP ingest (singles + batches) →
        every response 2xx, zero 5xx, and after recovery + drain the
        stored sequence exactly equals a no-outage run of the same
        traffic."""
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )

        n_clients, per_client = 4, 30
        # -- reference run: same traffic, healthy backend -------------
        ref = Storage({
            "PIO_STORAGE_SOURCES_M_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        })
        ref_app = ref.get_meta_data_apps().insert(App(0, "RefApp"))
        ref.get_events().init(ref_app)
        from predictionio_tpu.core.json_codec import event_from_json

        # insertion order is irrelevant to find()'s (eventTime, id)
        # ordering and every payload's eventTime is distinct, so the
        # reference sequence is deterministic
        for c in range(n_clients):
            for j in range(per_client):
                ref.get_events().insert(
                    event_from_json(event_payload(c, j)), ref_app)

        # -- chaos run ------------------------------------------------
        storage = chaos_storage("0.0")
        app_id = storage.get_meta_data_apps().insert(App(0, "WalApp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("walkey", app_id, ()))
        storage.get_events().init(app_id)
        server = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal")))
        server.start()
        chaos_client = storage.client_for_source("C")
        statuses: list[tuple[int, dict]] = []
        lock = threading.Lock()
        try:
            base = f"http://127.0.0.1:{server.port}"
            single_url = f"{base}/events.json?accessKey=walkey"
            batch_url = f"{base}/batch/events.json?accessKey=walkey"

            def client(c):
                for j in range(per_client):
                    if c == 0 and j % 3 == 2:
                        s, b = post_json(batch_url, [event_payload(c, j)])
                        result = (s if s >= 300 else b[0]["status"],
                                  b[0] if s < 300 else b)
                    else:
                        result = post_json(single_url, event_payload(c, j))
                    with lock:
                        statuses.append(result)
                    time.sleep(0.005)

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(n_clients)]
            for t in threads:
                t.start()
            # a beat of healthy traffic (warms the auth cache), then a
            # HARD outage window, then recovery
            time.sleep(0.15)
            chaos_client.injector.set_fault_rate(1.0)
            time.sleep(0.6)
            chaos_client.injector.set_fault_rate(0.0)
            for t in threads:
                t.join()

            # zero loss, zero 5xx: every accepted answer is 201 or 202
            codes = [s for s, _ in statuses]
            assert len(codes) == n_clients * per_client
            assert all(c in (201, 202) for c in codes), sorted(set(codes))
            assert 202 in codes, "outage window produced no journaled acks"
            assert 201 in codes, "healthy windows produced no direct acks"

            # drain completes (deadline-poll; the drainer races us)
            wal = server.service.wal
            assert wait_until(lambda: wal.pending_records() == 0), \
                wal.stats()
            assert wal.stats()["deadLetterTotal"] == 0

            # post-drain contents EXACTLY equal the no-outage run
            got = stored_sequence(storage, app_id)
            want = stored_sequence(ref, ref_app)
            assert got == want

            # every acknowledged id is the stored id (202s included)
            acked_ids = {b["eventId"] for s, b in statuses}
            stored_ids = {e.event_id for e in storage.get_events().find(
                app_id, None, EventFilter())}
            assert acked_ids == stored_ids

            # the mode gauge saw the ride-through and returned to idle
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            assert "pio_ingest_wal_mode 0" in metrics
            assert "pio_ingest_wal_replayed_total" in metrics
        finally:
            server.stop()
            storage.close()

    def test_disk_budget_flips_to_503_and_back(self, tmp_path):
        """Bounded honestly: at the WAL disk budget ingest sheds 503 +
        Retry-After again; once the backend recovers and the backlog
        drains, 2xx resumes."""
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )

        storage = chaos_storage("0.0")
        app_id = storage.get_meta_data_apps().insert(App(0, "BudgetApp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("bk", app_id, ()))
        storage.get_events().init(app_id)
        server = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0,
            wal_dir=str(tmp_path / "wal"), wal_max_bytes=4000))
        server.start()
        chaos_client = storage.client_for_source("C")
        try:
            url = (f"http://127.0.0.1:{server.port}"
                   "/events.json?accessKey=bk")
            assert post_json(url, event_payload(9, 0))[0] == 201  # warm
            chaos_client.injector.set_fault_rate(1.0)
            saw_202 = saw_503 = False
            retry_after = None
            for j in range(1, 120):
                s, body = post_json(url, event_payload(9, j))
                assert s in (202, 503), (s, body)
                saw_202 |= s == 202
                if s == 503:
                    saw_503 = True
                    break
            assert saw_202 and saw_503
            assert server.service.wal.is_full()
            # readyz is honest about a full journal during the outage
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/readyz", timeout=10)
            assert e.value.code == 503

            # recovery: drain empties the journal, acceptance resumes
            chaos_client.injector.set_fault_rate(0.0)
            wal = server.service.wal
            assert wait_until(lambda: wal.pending_records() == 0), \
                wal.stats()
            s, _ = post_json(url, event_payload(9, 500))
            assert s == 201
            assert not wal.is_full()
        finally:
            server.stop()
            storage.close()

    def test_write_through_policy_always_journals(self, tmp_path):
        """The top rung: every accepted event answers 202 and storage
        is written exclusively by the drainer."""
        from predictionio_tpu.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.utils.testing import memory_storage

        storage = memory_storage()
        app_id = storage.get_meta_data_apps().insert(App(0, "WtApp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("wt", app_id, ()))
        storage.get_events().init(app_id)
        server = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal"),
            wal_policy="write-through"))
        server.start()
        try:
            url = (f"http://127.0.0.1:{server.port}"
                   "/events.json?accessKey=wt")
            burl = (f"http://127.0.0.1:{server.port}"
                    "/batch/events.json?accessKey=wt")
            s, body = post_json(url, event_payload(1, 0))
            assert s == 202 and body["durability"] == "journaled"
            s, results = post_json(burl, [event_payload(1, 1),
                                          {"event": "x"},  # invalid
                                          event_payload(1, 2)])
            assert s == 200
            assert [r["status"] for r in results] == [202, 400, 202]
            assert wait_until(
                lambda: server.service.wal.pending_records() == 0)
            stored = list(storage.get_events().find(app_id))
            assert {e.entity_id for e in stored} == {
                "c1-u0", "c1-u1", "c1-u2"}
        finally:
            server.stop()
            storage.close()


class TestKill9Recovery:
    def test_kill9_mid_journal_truncates_torn_tail_and_replays(
            self, tmp_path):
        """kill -9 the event server while clients stream journaled
        writes; recovery truncates the torn tail (simulated on top of
        whatever the kill left) and replays EVERY acknowledged event —
        fsync=always means a 202 is a durability promise that must
        survive SIGKILL."""
        wal_dir = str(tmp_path / "wal")
        db = str(tmp_path / "child.db")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "wal_eventserver_child.py"),
             "--db", db, "--wal-dir", wal_dir],
            stdout=subprocess.PIPE, text=True)
        acked: list[tuple[str, str]] = []   # (entityId, eventId)
        try:
            app_id = port = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and port is None:
                line = proc.stdout.readline().strip()
                if line.startswith("APP_ID="):
                    app_id = int(line.split("=", 1)[1])
                elif line.startswith("PORT="):
                    port = int(line.split("=", 1)[1])
            assert app_id is not None and port is not None, \
                "child never became ready"
            url = f"http://127.0.0.1:{port}/events.json?accessKey=walkey"
            kill_after = 20
            for j in range(200):
                payload = event_payload(0, j)
                try:
                    s, body = post_json(url, payload)
                except (ConnectionError, OSError):
                    break  # the kill ripped this connection
                if s == 202:
                    acked.append((payload["entityId"], body["eventId"]))
                if len(acked) == kill_after:
                    # SIGKILL mid-stream: no flush, no atexit, nothing
                    os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            assert len(acked) >= kill_after
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

        # simulate the worst mid-append artifact on top of the real
        # kill state: a partial frame at the tail of the last segment
        segs = sorted(f for f in os.listdir(wal_dir)
                      if f.startswith("wal-") and f.endswith(".seg"))
        assert segs, "child journaled nothing"
        with open(os.path.join(wal_dir, segs[-1]), "ab") as f:
            f.write(b"\xde\xad\xbe")  # torn: shorter than a header

        # recovery + replay into a fresh healthy store
        from predictionio_tpu.data.wal import WalDrainer, WriteAheadLog
        from predictionio_tpu.utils.testing import memory_storage

        out = memory_storage()
        out.get_events().init(app_id)
        wal = WriteAheadLog(wal_dir)
        assert wal.torn_bytes_truncated >= 3
        drainer = WalDrainer(wal, out.get_events().insert_batch)
        while wal.pending_records():
            verdict = drainer.drain_once()
            assert verdict in ("progress", "empty"), verdict
        assert wal.stats()["deadLetterTotal"] == 0

        stored = {(e.entity_id, e.event_id)
                  for e in out.get_events().find(app_id)}
        # every 202-acknowledged event survived the SIGKILL, under the
        # exact id the client was given
        assert set(acked) <= stored
