"""CLI train/eval/deploy/undeploy round-trip — the quickstart lifecycle
(reference: tests/pio_tests/scenarios/quickstart_test.py) driven through
`pio` with default sqlite storage in an isolated basedir."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from predictionio_tpu.cli.pio import main
from predictionio_tpu.storage.registry import Storage


@pytest.fixture
def cli_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    monkeypatch.chdir(tmp_path)
    Storage.reset_default()
    yield tmp_path
    Storage.reset_default()


def test_train_eval_deploy_undeploy(cli_env, capsys):
    engine_json = {
        "id": "cli-engine",
        "engineFactory": "tests.sample_engine.engine_factory",
        "datasource": {"params": {"id": 3, "n_train": 5, "n_folds": 2}},
        "algorithms": [{"name": "sample", "params": {"id": 0, "mult": 4}}],
    }
    (cli_env / "engine.json").write_text(json.dumps(engine_json))

    # train
    assert main(["train"]) == 0
    out = capsys.readouterr().out
    assert "COMPLETED" in out

    # eval (evaluation + generator live in the test support module)
    assert main([
        "eval",
        "tests.cli_eval_support.CliEvaluation",
        "tests.cli_eval_support.CliParamsList",
    ]) == 0
    out = capsys.readouterr().out
    assert "Evaluation finished" in out

    # deploy on an ephemeral port, serve_forever on a thread
    t = threading.Thread(
        target=main, args=(["deploy", "--ip", "127.0.0.1", "--port", "18432"],),
        daemon=True,
    )
    t.start()
    deadline = time.time() + 10
    status = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen("http://127.0.0.1:18432/", timeout=2) as r:
                status = json.loads(r.read())
            break
        except OSError:
            time.sleep(0.1)
    assert status and status["status"] == "alive"

    req = urllib.request.Request(
        "http://127.0.0.1:18432/queries.json",
        data=json.dumps({"x": 2}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        result = json.loads(r.read())
    assert result["value"] == 8  # mult=4

    # undeploy stops it
    assert main(["undeploy", "--ip", "127.0.0.1", "--port", "18432"]) == 0
    t.join(timeout=5)
    assert not t.is_alive()


def test_train_missing_engine_json_fails(cli_env, capsys):
    assert main(["train", "--engine-json", "nope.json"]) == 1
    assert "not found" in capsys.readouterr().out


def test_build_validates_variant(cli_env, capsys):
    engine_json = {
        "id": "cli-engine",
        "engineFactory": "tests.sample_engine.engine_factory",
        "datasource": {"params": {"id": 3, "n_train": 5, "n_folds": 2}},
        "algorithms": [{"name": "sample", "params": {"id": 0, "mult": 3}}],
    }
    with open("engine.json", "w") as f:
        json.dump(engine_json, f)
    assert main(["build"]) == 0
    assert "Build successful" in capsys.readouterr().out

    # bad factory fails
    engine_json["engineFactory"] = "tests.sample_engine.no_such_factory"
    with open("engine.json", "w") as f:
        json.dump(engine_json, f)
    assert main(["build"]) == 1
    assert "failed" in capsys.readouterr().out

    # unbindable params fail
    engine_json["engineFactory"] = "tests.sample_engine.engine_factory"
    engine_json["algorithms"] = [{"name": "no-such-algo", "params": {}}]
    with open("engine.json", "w") as f:
        json.dump(engine_json, f)
    assert main(["build"]) == 1
    assert "do not bind" in capsys.readouterr().out


def test_run_invokes_target_main(cli_env, capsys):
    assert main(["run", "tests.cli_eval_support:run_target", "a", "b"]) == 0
    assert "run_target(a, b)" in capsys.readouterr().out
    assert main(["run", "tests.no_such_module:main"]) == 1


def test_upgrade_and_template_report_unsupported(cli_env, capsys):
    # Parity: Console.scala:664-666, 691-694
    assert main(["upgrade"]) == 1
    assert main(["template", "get", "x"]) == 1
    out = capsys.readouterr().out
    assert "no longer supported" in out


def test_module_entrypoint_registers_workflow_commands(cli_env):
    # `python -m predictionio_tpu.cli.pio` must expose train/deploy —
    # regression test for the __main__ double-import dropping them.
    import os
    import pathlib
    import subprocess
    import sys

    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ, PYTHONPATH=repo_root)
    out = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.cli.pio", "--help"],
        capture_output=True, text=True, env=env,
    ).stdout
    for cmd in ("train", "deploy", "eval", "build"):
        assert cmd in out
