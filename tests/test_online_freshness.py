"""Real-time freshness plane (predictionio_tpu/online/): closed-form
fold-in units, overlay generation fencing, and the e2e pin — a rating
POSTed to the event server changes that user's /queries.json
recommendations within the tail interval, no retrain, zero 5xx
(ISSUE 14 acceptance)."""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.online.foldin import (
    item_gramian,
    popularity_prior,
    solve_user,
)
from predictionio_tpu.online.follower import CursorStore, TailCursor
from predictionio_tpu.online.overlay import ItemDelta, OnlineOverlay, UserDelta
from predictionio_tpu.online.service import user_key_fragment
from predictionio_tpu.storage.base import AccessKey, App
from predictionio_tpu.workflow.train import run_train

pytestmark = pytest.mark.online

RANK = 8
LAM = 0.05

REC_VARIANT = {
    "id": "rec",
    "engineFactory":
        "predictionio_tpu.templates.recommendation.engine_factory",
    "datasource": {"params": {"app_name": "RecApp"}},
    "algorithms": [
        {"name": "als",
         "params": {"rank": RANK, "num_iterations": 8, "lambda_": LAM,
                    "seed": 1}}
    ],
}


def _event(event, user, item, props=None, **kw):
    return Event(event=event, entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties=DataMap(props or {}), **kw)


def _seed_and_train(storage, monkeypatch, tmp_path):
    app_id = storage.get_meta_data_apps().insert(App(0, "RecApp"))
    storage.get_meta_data_access_keys().insert(
        AccessKey("fresh-key", app_id, []))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(0)
    for u in range(16):
        for i in range(12):
            if i % 2 == u % 2 and rng.random() < 0.8:
                events.insert(
                    _event("rate", f"u{u}", f"i{i}", {"rating": 5.0}),
                    app_id)
            elif rng.random() < 0.1:
                events.insert(
                    _event("rate", f"u{u}", f"i{i}", {"rating": 1.0}),
                    app_id)
    monkeypatch.setenv("PIO_MODEL_DIR", str(tmp_path))
    outcome = run_train(variant=REC_VARIANT, storage=storage)
    assert outcome.status == "COMPLETED"
    return app_id


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _query(port, user, num=5):
    status, body = _post(f"http://127.0.0.1:{port}/queries.json",
                         {"user": user, "num": num})
    assert status == 200
    return [s["item"] for s in body["itemScores"]], body


# ---------------------------------------------------------------------------
# units: closed-form solves
# ---------------------------------------------------------------------------

class TestFoldInMath:
    def test_explicit_matches_normal_equations(self):
        rng = np.random.default_rng(3)
        Y = rng.normal(size=(7, RANK)).astype(np.float32)
        r = rng.uniform(1, 5, size=7).astype(np.float32)
        u = solve_user(Y, r, lam=LAM)
        # independent reference: ALS-WR normal equations
        A = Y.T @ Y + LAM * 7 * np.eye(RANK, dtype=np.float32)
        np.testing.assert_allclose(A @ u, r @ Y, rtol=1e-4, atol=1e-4)

    def test_implicit_matches_hu_koren(self):
        rng = np.random.default_rng(4)
        Y = rng.normal(size=(64, RANK)).astype(np.float32)
        obs = Y[:5]
        r = np.asarray([1, 1, 2, -1, 0], dtype=np.float32)
        gram = item_gramian(Y)
        u = solve_user(obs, r, lam=LAM, implicit=True, alpha=2.0,
                       gram=gram)
        w = 2.0 * np.abs(r)
        A = gram + (obs * w[:, None]).T @ obs + LAM * np.eye(RANK)
        b = np.where(r > 0, 1.0 + 2.0 * r, 0.0) @ obs
        np.testing.assert_allclose(A @ u, b, rtol=1e-4, atol=1e-4)

    def test_implicit_requires_gramian(self):
        with pytest.raises(ValueError):
            solve_user(np.ones((2, RANK), np.float32),
                       np.ones(2, np.float32), lam=LAM, implicit=True)

    def test_empty_interactions_solve_to_none(self):
        assert solve_user(np.zeros((0, RANK), np.float32),
                          np.zeros(0, np.float32), lam=LAM) is None

    def test_popularity_prior_is_weighted_centroid(self):
        table = np.asarray([[1.0, 0.0], [0.0, 1.0]], dtype=np.float32)
        np.testing.assert_allclose(popularity_prior(table), [0.5, 0.5])
        np.testing.assert_allclose(
            popularity_prior(table, weights=np.asarray([3.0, 1.0])),
            [0.75, 0.25])


# ---------------------------------------------------------------------------
# units: overlay fencing + bounds, cursor store
# ---------------------------------------------------------------------------

class TestOverlay:
    def _delta(self, seed=0):
        return UserDelta(vector=np.full((RANK,), float(seed),
                                        dtype=np.float32))

    def test_generation_fencing_discards_stale_puts(self):
        ov = OnlineOverlay(generation=5)
        assert ov.put_user("u1", self._delta(), generation=5)
        ov.advance_generation(6)
        assert ov.user("u1") is None            # cleared with the swap
        assert not ov.put_user("u2", self._delta(), generation=5)
        assert ov.user("u2") is None
        assert ov.counters()["fenced"] == 1
        assert ov.put_user("u2", self._delta(), generation=6)

    def test_generation_only_moves_forward(self):
        ov = OnlineOverlay(generation=9)
        ov.advance_generation(3)                # lagging doc can't rewind
        assert ov.generation == 10

    def test_lru_bound_and_eviction_count(self):
        ov = OnlineOverlay(max_users=2)
        for i in range(4):
            assert ov.put_user(f"u{i}", self._delta(i), generation=0)
        assert ov.counters() == {
            "users": 2, "items": 0, "evictions": 2, "fenced": 0,
            "generation": 0}
        assert ov.user("u0") is None and ov.user("u3") is not None

    def test_delta_matrix_caches_and_rebuilds(self):
        ov = OnlineOverlay()
        assert ov.delta_matrix() is None
        ov.put_item("a", ItemDelta(np.ones(RANK, np.float32)),
                    generation=0)
        ids, m1 = ov.delta_matrix()
        assert ids == ("a",) and m1.shape == (1, RANK)
        assert ov.delta_matrix()[1] is m1       # cached
        ov.put_item("b", ItemDelta(np.zeros(RANK, np.float32)),
                    generation=0)
        ids2, m2 = ov.delta_matrix()
        assert ids2 == ("a", "b") and m2.shape == (2, RANK)

    def test_follower_backlog_is_paged_not_materialized(self):
        """A poll against a deep backlog stops at max_rows with the
        cursor on the last row CONSUMED — the next poll continues
        exactly there (paged, still exactly-once; the post-outage
        resume must not materialize a whole weekend in one pass)."""
        from predictionio_tpu.online.follower import EventTailFollower
        from predictionio_tpu.storage.memory import MemoryStorageClient

        events = MemoryStorageClient().events()
        events.init(1)
        events.insert_batch(
            [_event("rate", f"u{i % 5}", f"i{i % 7}", {"rating": 1.0})
             for i in range(25)], 1)
        follower = EventTailFollower(events, 1, batch_size=4, max_rows=10)
        seen = []
        for _ in range(5):
            rows, cursor = follower.poll_once()
            assert len(rows) <= 10
            seen.extend(r.event_id for r in rows)
            follower.commit(cursor)
            if not rows:
                break
        full = [e.event_id for e in events.find(1)]
        assert seen == full            # no skip, no duplicate, all pages

    def test_cursor_store_round_trip_and_junk(self, tmp_path):
        path = str(tmp_path / "cursor.json")
        store = CursorStore(path)
        assert store.load() is None
        store.save(TailCursor(12345, "abc"))
        assert CursorStore(path).load() == TailCursor(12345, "abc")
        with open(path, "w") as f:
            f.write("{not json")
        assert CursorStore(path).load() is None

    def test_user_key_fragment_matches_cache_keys(self):
        from predictionio_tpu.core.json_codec import canonical_json

        key = canonical_json({"num": 5, "user": "u1"})
        assert user_key_fragment("u1") in key
        assert user_key_fragment("u11") not in key

    def test_result_cache_invalidate_matching_is_targeted(self):
        from predictionio_tpu.serving.result_cache import ResultCache

        cache = ResultCache()
        cache.put('{"num":5,"user":"u1"}', 1)
        cache.put('{"num":9,"user":"u1"}', 2)
        cache.put('{"num":5,"user":"u2"}', 3)
        gen = cache.generation
        assert cache.invalidate_matching(user_key_fragment("u1")) == 2
        assert len(cache) == 1
        # other users' ENTRIES survive (nothing cleared pool-wide)...
        assert cache.lookup('{"num":5,"user":"u2"}')[0]
        # ...but the generation advances so a pre-fold in-flight
        # computation (even for a user with no entry yet) cannot put()
        # its stale result back
        assert cache.generation > gen
        assert not cache.put('{"num":5,"user":"u1"}', "stale",
                             generation=gen)
        assert cache.stats.count("cache_user_invalidations") == 2


# ---------------------------------------------------------------------------
# e2e: event server POST -> fold -> /queries.json freshness
# ---------------------------------------------------------------------------

@pytest.fixture
def deployed(storage, monkeypatch, tmp_path):
    from predictionio_tpu.api.engine_server import create_engine_server
    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.workflow.deploy import ServerConfig

    _seed_and_train(storage, monkeypatch, tmp_path)
    engine = create_engine_server(storage=storage, config=ServerConfig(
        ip="127.0.0.1", port=0, online=True, online_interval_s=0.05,
        cache_enabled=True, tracing=True))
    engine.start()
    eventsrv = EventServer(
        storage, EventServerConfig(ip="127.0.0.1", port=0))
    eventsrv.start()
    yield engine, eventsrv, storage
    eventsrv.stop()
    engine.stop()


class TestFreshnessE2E:
    def test_rating_posted_changes_recommendations_no_retrain(
            self, deployed):
        engine, eventsrv, storage = deployed
        svc = engine.service
        assert svc.online is not None and svc.online.enabled
        before, _ = _query(engine.port, "u0", 6)
        assert before, "trained user must be served"
        target = before[0]                      # the current favorite
        # POST the rating through the event server front door
        status, body = _post(
            f"http://127.0.0.1:{eventsrv.port}/events.json"
            "?accessKey=fresh-key",
            {"event": "rate", "entityType": "user", "entityId": "u0",
             "targetEntityType": "item", "targetEntityId": target,
             "properties": {"rating": 5.0}})
        assert status == 201
        # deadline-poll (never assert the first read): the fold lands
        # within a few tail intervals; every poll must be a 200
        deadline = time.time() + 15
        after = before
        while time.time() < deadline:
            after, _ = _query(engine.port, "u0", 6)
            if after != before:
                break
            time.sleep(0.05)
        assert after != before, "fold-in never reached serving"
        # the just-rated item is now SEEN: excluded from the answer
        assert target not in after
        # no retrain happened: same engine instance is serving
        assert svc.deployed.instance.id
        metrics = svc.online.metrics()
        assert metrics["foldedEventsTotal"] >= 1
        assert metrics["usersFoldedTotal"] >= 1
        assert metrics["lagSeconds"] is not None

    def test_folded_vector_matches_reference_solve(self, deployed):
        engine, eventsrv, storage = deployed
        svc = engine.service
        app = storage.get_meta_data_apps().get_by_name("RecApp")
        storage.get_events().insert(
            _event("rate", "u1", "i0", {"rating": 4.0}), app.id)
        deadline = time.time() + 15
        while time.time() < deadline:
            if svc.online.overlay.user("u1") is not None:
                break
            time.sleep(0.05)
        delta = svc.online.overlay.user("u1")
        assert delta is not None
        # from-scratch reference: the user's FULL history against the
        # deployed item table, solved with plain numpy ALS-WR normal
        # equations (independent of the service's code path)
        model = svc.online._binding.model
        Y = np.asarray(model.item_factors)
        ixs, ratings = [], []
        for e in storage.get_events().find(app.id):
            if e.entity_id != "u1" or e.target_entity_id is None:
                continue
            if e.event == "rate":
                ratings.append(float(e.properties.fields["rating"]))
            else:
                ratings.append(4.0)
            ixs.append(model.item_ids.get(e.target_entity_id))
        obs = Y[np.asarray(ixs)]
        n = len(ixs)
        A = obs.T @ obs + LAM * n * np.eye(RANK, dtype=np.float32)
        ref = np.linalg.solve(A, np.asarray(ratings, np.float32) @ obs)
        np.testing.assert_allclose(delta.vector, ref, rtol=1e-3,
                                   atol=1e-4)

    def test_cold_start_user_and_item_are_served(self, deployed):
        engine, eventsrv, storage = deployed
        # unknown user before: empty answer (reference behavior)
        empty, _ = _query(engine.port, "brand-new-user", 5)
        assert empty == []
        for iid in ("i0", "i2", "i4"):
            status, _ = _post(
                f"http://127.0.0.1:{eventsrv.port}/events.json"
                "?accessKey=fresh-key",
                {"event": "rate", "entityType": "user",
                 "entityId": "brand-new-user", "targetEntityType": "item",
                 "targetEntityId": iid, "properties": {"rating": 5.0}})
            assert status == 201
        # ...and a brand-new ITEM rated by a known even-taste user
        status, _ = _post(
            f"http://127.0.0.1:{eventsrv.port}/events.json"
            "?accessKey=fresh-key",
            {"event": "rate", "entityType": "user", "entityId": "u2",
             "targetEntityType": "item", "targetEntityId": "fresh-item",
             "properties": {"rating": 5.0}})
        assert status == 201
        deadline = time.time() + 15
        served: list = []
        while time.time() < deadline:
            served, _ = _query(engine.port, "brand-new-user", 5)
            if served:
                break
            time.sleep(0.05)
        assert served, "cold-start user never served"
        # the new user liked EVEN items; the folded vector must rank
        # unseen even items above odd ones
        evens = [i for i in served if i.startswith("i")
                 and int(i[1:]) % 2 == 0]
        assert len(evens) >= len(served) // 2
        # the overlay item is servable to OTHER users (merged into
        # the top-k without an index rebuild)
        deadline = time.time() + 15
        got_fresh = False
        while time.time() < deadline:
            recs, _ = _query(engine.port, "u0", 12)
            if "fresh-item" in recs:
                got_fresh = True
                break
            time.sleep(0.05)
        assert got_fresh, "overlay item never merged into serving"

    def test_observability_stats_metrics_and_spans(self, deployed):
        engine, eventsrv, storage = deployed
        app = storage.get_meta_data_apps().get_by_name("RecApp")
        storage.get_events().insert(
            _event("rate", "u3", "i1", {"rating": 5.0}), app.id)
        deadline = time.time() + 15
        while time.time() < deadline:
            if engine.service.online.metrics()["foldedEventsTotal"] >= 1:
                break
            time.sleep(0.05)
        doc = json.loads(_get(
            f"http://127.0.0.1:{engine.port}/stats.json"))
        online = doc["online"]
        assert online["enabled"] is True
        assert online["foldedEventsTotal"] >= 1
        assert online["overlayUsers"] >= 1
        assert online["lagSeconds"] > 0
        assert online["cursor"] is not None
        text = _get(
            f"http://127.0.0.1:{engine.port}/metrics").decode()
        for family in ("pio_online_folded_events_total",
                       "pio_online_fold_cycles_total",
                       "pio_online_overlay_size",
                       "pio_online_freshness_lag_seconds",
                       "pio_online_enabled"):
            assert family in text, f"{family} missing from /metrics"
        traces = json.loads(_get(
            f"http://127.0.0.1:{engine.port}/traces.json"))["traces"]
        folds = [t for t in traces if t["name"] == "online.foldin"]
        assert folds, "fold cycle left no trace in the ring"
        span_names = {s["name"] for s in folds[0]["spans"]}
        assert {"tail", "solve", "publish"} <= span_names

    def test_generation_fencing_on_reload(self, deployed):
        """An overlay computed against model generation G is discarded,
        never applied, after /reload lands G+1 (ISSUE 14 acceptance)."""
        engine, eventsrv, storage = deployed
        svc = engine.service
        app = storage.get_meta_data_apps().get_by_name("RecApp")
        storage.get_events().insert(
            _event("rate", "u4", "i2", {"rating": 5.0}), app.id)
        deadline = time.time() + 15
        while time.time() < deadline:
            if svc.online.overlay.user("u4") is not None:
                break
            time.sleep(0.05)
        assert svc.online.overlay.user("u4") is not None
        stale_gen = svc.model_generation
        stale = UserDelta(vector=np.ones((RANK,), dtype=np.float32))
        # /reload: the generation fence advances and clears the overlay
        status, _ = _post(
            f"http://127.0.0.1:{engine.port}/reload", {})
        assert status == 200
        assert svc.model_generation == stale_gen + 1
        assert svc.online.overlay.user("u4") is None
        # the pre-reload fold can never land on the new model
        assert not svc.online.overlay.put_user(
            "u4", stale, generation=stale_gen)
        assert svc.online.metrics()["fenced"] >= 1
        # ...but the refold queue re-solves u4 against the NEW model
        deadline = time.time() + 15
        while time.time() < deadline:
            if svc.online.overlay.user("u4") is not None:
                break
            time.sleep(0.05)
        refolded = svc.online.overlay.user("u4")
        assert refolded is not None
        assert not np.allclose(refolded.vector, stale.vector)

    def test_per_user_cache_invalidation_not_pool_wide(self, deployed):
        engine, eventsrv, storage = deployed
        svc = engine.service
        # warm two users' cache entries
        _query(engine.port, "u5", 5)
        _query(engine.port, "u6", 5)
        app = storage.get_meta_data_apps().get_by_name("RecApp")
        storage.get_events().insert(
            _event("rate", "u5", "i3", {"rating": 5.0}), app.id)
        deadline = time.time() + 15
        while time.time() < deadline:
            if svc.online.overlay.user("u5") is not None:
                break
            time.sleep(0.05)
        assert svc.online.overlay.user("u5") is not None
        # u5's entry died, u6's survived the fold (entries are never
        # cleared pool-wide by the targeted path)
        assert svc.serving_stats.count("cache_user_invalidations") >= 1
        keys = list(svc.cache._entries)
        assert any(user_key_fragment("u6") in k for k in keys)
        assert not any(user_key_fragment("u5") in k for k in keys)


# ---------------------------------------------------------------------------
# e2e: --workers 2 propagation over the spool plane
# ---------------------------------------------------------------------------

class TestWorkersPropagation:
    def test_fold_reaches_every_sibling(self, storage, monkeypatch,
                                        tmp_path):
        from predictionio_tpu.api.engine_server import (
            create_engine_server,
        )
        from predictionio_tpu.workflow.deploy import ServerConfig

        _seed_and_train(storage, monkeypatch, tmp_path)
        spool = str(tmp_path / "spool")
        servers = []
        try:
            for _ in range(2):
                s = create_engine_server(
                    storage=storage,
                    config=ServerConfig(
                        ip="127.0.0.1", port=0, online=True,
                        online_interval_s=0.05, worker_spool_dir=spool,
                        admin_sync_interval_s=0.05))
                s.start()
                servers.append(s)
            # exactly one lease-holding leader folds; the sibling syncs
            deadline = time.time() + 10
            while time.time() < deadline:
                leaders = [s.service.online.metrics()["leader"]
                           for s in servers]
                if sum(leaders) == 1:
                    break
                time.sleep(0.05)
            assert sum(s.service.online.metrics()["leader"]
                       for s in servers) == 1
            app = storage.get_meta_data_apps().get_by_name("RecApp")
            storage.get_events().insert(
                _event("rate", "u0", "i1", {"rating": 5.0}), app.id)
            # the fold must reach BOTH workers' overlays (leader folds,
            # sibling adopts the published snapshot)
            deadline = time.time() + 15
            while time.time() < deadline:
                if all(s.service.online.overlay.user("u0") is not None
                       for s in servers):
                    break
                time.sleep(0.05)
            vectors = []
            for s in servers:
                delta = s.service.online.overlay.user("u0")
                assert delta is not None, "sibling never adopted the fold"
                vectors.append(delta.vector)
            np.testing.assert_allclose(vectors[0], vectors[1])
            # and BOTH workers' query paths serve the folded state:
            # i1 is now seen for u0 on either port
            for s in servers:
                recs, _ = _query(s.port, "u0", 6)
                assert "i1" not in recs
        finally:
            for s in servers:
                s.stop()

    def test_dead_leader_lease_is_reclaimed(self, tmp_path):
        from predictionio_tpu.online.service import TailLease

        spool = str(tmp_path)
        a = TailLease(spool, "worker-a")
        assert a.try_hold() and a.try_hold()     # idempotent
        b = TailLease(spool, "worker-b")
        assert not b.try_hold()                  # live holder elsewhere
        # fake the holder's death: rewrite the lease with a dead pid
        with open(a.path, "w") as f:
            json.dump({"worker": "worker-a", "pid": 2 ** 22 + 12345}, f)
        assert b.try_hold()                      # reaped + claimed
        assert not a.try_hold()
