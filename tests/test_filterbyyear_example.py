"""Scenario test for examples/similarproduct-filterbyyear — the
reference's filterbyyear variant (examples/scala-parallel-similarproduct/
filterbyyear/): required item 'year' property read at train time,
query-time year filter, year-enriched results. Driven through the real
train workflow and HTTP serving."""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.train import run_train

EXAMPLE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "examples",
    "similarproduct-filterbyyear",
)


@pytest.fixture
def example_engine():
    sys.path.insert(0, EXAMPLE_DIR)
    sys.modules.pop("engine", None)
    try:
        import engine

        yield engine
    finally:
        sys.path.remove(EXAMPLE_DIR)
        sys.modules.pop("engine", None)


def _seed(storage, with_years=True):
    app_id = storage.get_meta_data_apps().insert(App(0, "FilterByYearApp"))
    events = storage.get_events()
    events.init(app_id)
    rng = np.random.default_rng(11)
    for i in range(16):
        props = {"year": 1990 + i} if with_years else {"other": 1}
        events.insert(
            Event(event="$set", entity_type="item", entity_id=f"i{i}",
                  properties=DataMap(props)), app_id)
    for u in range(20):
        for i in range(16):
            if i % 2 == u % 2 and rng.random() < 0.8:
                events.insert(
                    Event(event="view", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}", properties=DataMap({})),
                    app_id)
    return storage


def _variant():
    with open(os.path.join(EXAMPLE_DIR, "engine.json")) as f:
        variant = json.load(f)
    variant["algorithms"][0]["params"]["use_mesh"] = False
    return variant


def test_year_filter_and_enriched_result(example_engine, storage):
    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.workflow.context import EngineContext
    from predictionio_tpu.workflow.deploy import (
        DeployedEngine,
        ServerConfig,
    )
    from predictionio_tpu.workflow.persistence import load_models

    seeded = _seed(storage)
    variant = _variant()
    outcome = run_train(variant=variant, storage=seeded)
    assert outcome.status == "COMPLETED"

    eng = example_engine.engine_factory()
    ep = eng.params_from_variant_json(variant)
    ctx = EngineContext(storage=seeded)
    _, _, algos, serving = eng.make_components(ep)
    models = eng.prepare_deploy(
        ctx, ep, load_models(seeded, outcome.instance_id), algorithms=algos)
    # the persisted round-trip must preserve the years map
    assert models[0].years["i7"] == 1997

    instance = seeded.get_meta_data_engine_instances().get(
        outcome.instance_id)
    server = EngineServer(
        DeployedEngine(None, instance, algos, serving, models),
        ServerConfig(ip="127.0.0.1", port=0))
    server.start()
    try:
        def query(body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/queries.json",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())["itemScores"]

        base = query({"items": ["i2"], "num": 5})
        assert base, "no similar items"
        # every score is year-enriched (reference ItemScore parity)
        for s in base:
            assert s["year"] == 1990 + int(s["item"][1:])

        # recommendFromYear filters strictly: year > 1997 only
        recent = query({"items": ["i2"], "num": 5,
                        "recommendFromYear": 1997})
        assert recent, "year filter returned nothing"
        assert all(s["year"] > 1997 for s in recent), recent

        # default (reference getOrElse(1)): everything eligible
        assert len(base) == 5
    finally:
        server.stop()


def test_missing_year_fails_training_loudly(example_engine, storage):
    """Reference parity: DataSource.scala:88-96 throws when a $set item
    has no year — the instance is marked FAILED and the error surfaces."""
    seeded = _seed(storage, with_years=False)
    with pytest.raises(ValueError, match="no 'year' property"):
        run_train(variant=_variant(), storage=seeded)
    instances = seeded.get_meta_data_engine_instances().get_all()
    assert any(i.status == "FAILED" for i in instances)
