"""Forced-8-device child for the `mesh` lane (spawned by the conftest
``run_mesh_child`` helper — tests/test_mesh_conformance.py): proves the
DP×MP story end to end in a FRESH process where the env knobs actually
steer training and load, exactly as `pio train` / `pio deploy` would
see them.

With ``PIO_TRAIN_SHARD_FACTORS=1`` in the environment (set by the
parent): trains the flagship fused layout twice — replicated baseline
vs env-forced ``shard_factors`` over every serving mesh shape (1×8,
2×4, 4×2) — pins factor parity, then saves the sharded model, reloads
it through the auto-sharding ``ALSModel.load`` path, and pins sharded
top-k serving equal to the replicated brute dispatch. Prints the
per-shape verdicts and ``MESH PARITY OK`` on success.
"""

import os
import tempfile

import numpy as np

from predictionio_tpu.utils.testing import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from predictionio_tpu.models.als import ALSModel  # noqa: E402
from predictionio_tpu.ops.als import (  # noqa: E402
    RatingsCOO,
    als_train,
    resolve_shard_factors,
)
from predictionio_tpu.utils.bimap import BiMap, EntityIdIxMap  # noqa: E402

assert jax.device_count() == 8, jax.device_count()
# the parent exports PIO_TRAIN_SHARD_FACTORS=1: the env override, not a
# call-site param, is what turns sharding on below (the fleet knob)
assert os.environ.get("PIO_TRAIN_SHARD_FACTORS") == "1"
assert resolve_shard_factors(False) is True

rng = np.random.default_rng(7)
nnz = 10_000
users, items = 96, 64  # divide every model-axis width below exactly
coo = RatingsCOO(
    (users * rng.random(nnz) ** 1.6).astype(np.int32),
    (items * rng.random(nnz) ** 1.6).astype(np.int32),
    (rng.random(nnz) * 5).astype(np.float32), users, items,
)

replicated = als_train(coo, rank=8, iterations=3, lam=0.05, seed=3,
                       layout="fused", matmul_dtype="float32")

for shape in ((1, 8), (2, 4), (4, 2)):
    mesh = Mesh(np.asarray(jax.devices()).reshape(shape),
                ("data", "model"))
    sharded = als_train(
        coo, rank=8, iterations=3, lam=0.05, seed=3, mesh=mesh,
        layout="fused", matmul_dtype="float32",
        shard_factors=resolve_shard_factors(False))
    np.testing.assert_allclose(np.asarray(replicated.user),
                               np.asarray(sharded.user),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(replicated.item),
                               np.asarray(sharded.item),
                               rtol=2e-4, atol=2e-4)
    model_ax = int(shape[1])
    spec = sharded.item.sharding.spec
    assert spec and spec[0] == "model", spec
    print(f"parity {shape[0]}x{shape[1]}: OK")

# train-sharded model -> save (persists `sharded` meta) -> plain load()
# (the template/deploy call shape) -> sharded serving == replicated
mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
factors = als_train(coo, rank=8, iterations=3, lam=0.05, seed=3,
                    mesh=mesh, layout="fused", matmul_dtype="float32",
                    shard_factors=resolve_shard_factors(False))
user_ids = EntityIdIxMap(BiMap({f"u{i}": i for i in range(users)}))
item_ids = EntityIdIxMap(BiMap({f"i{i}": i for i in range(items)}))
seen = {0: np.asarray([1, 2, 3], dtype=np.int32)}
model = ALSModel(rank=8, user_factors=factors.user,
                 item_factors=factors.item, user_ids=user_ids,
                 item_ids=item_ids, seen_by_user=seen)
assert model.factor_shard_ways == 4

os.environ["PIO_SERVING_ANN_BUILD"] = "0"
with tempfile.TemporaryDirectory() as d:
    model.save(d)
    loaded = ALSModel.load(d)            # auto-resharded from meta
    assert loaded.factor_shard_ways > 1, loaded.factor_shard_ways
    os.environ["PIO_SERVING_SHARD_FACTORS"] = "0"
    brute = ALSModel.load(d)             # env veto: replicated
    assert brute.factor_shard_ways == 1

for uid in ("u0", "u7", "u41"):
    a = brute.recommend(uid, 10)
    b = loaded.recommend(uid, 10)
    assert [x[0] for x in a] == [x[0] for x in b], (uid, a, b)
    assert np.allclose([x[1] for x in a], [x[1] for x in b], atol=1e-5)
print("serving equality: OK")
print("MESH PARITY OK")
