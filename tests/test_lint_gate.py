"""Tier-1 gate: `pio lint` must pass CLEAN over the real package.

This is the machine-checked form of the invariants that previously
lived in reviewers' heads: every finding here is either a genuine new
violation (fix it) or a deliberate exception (suppress it inline WITH a
justification — see docs/static-analysis.md). The gate runs every
registered rule with the repo policy config, exactly what
`bin/pio-lint` runs in CI.
"""

from __future__ import annotations

import pytest

from predictionio_tpu.analysis import (
    all_rules,
    default_config,
    format_findings,
    lint_package,
)

pytestmark = pytest.mark.lint

EXPECTED_RULES = {
    "resilience-bypass",
    "jit-purity",
    "host-sync-in-hot-path",
    "dtype-discipline",
    "untimed-blocking-io",
    "lock-discipline",
}


def test_rule_suite_is_complete():
    """The gate is only as strong as its rule set: all six invariant
    families must be registered AND enabled in the repo policy."""
    registered = set(all_rules())
    assert EXPECTED_RULES <= registered
    enabled = set(default_config().enabled_rules())
    assert EXPECTED_RULES <= enabled


def test_package_lints_clean():
    """All rules over all of predictionio_tpu/: zero findings. A failure
    message IS the lint report — fix the violation or suppress it with
    a justification at the site."""
    findings = lint_package()
    assert not findings, "\n" + format_findings(findings)


def test_every_rule_actually_runs_on_the_package():
    """Guard against a rule silently scoping itself out of existence:
    each rule's configured paths must match at least one real file."""
    import os

    import predictionio_tpu

    from predictionio_tpu.analysis.config import path_matches

    pkg = os.path.dirname(predictionio_tpu.__file__)
    relpaths = [
        os.path.relpath(os.path.join(dirpath, f), pkg).replace(os.sep, "/")
        for dirpath, _, files in os.walk(pkg)
        for f in files
        if f.endswith(".py")
    ]
    config = default_config()
    for rule_id, rule in all_rules().items():
        prefixes = config.rule_paths(rule)
        assert any(path_matches(rp, prefixes) for rp in relpaths), (
            f"{rule_id}: configured paths {prefixes} match no file under "
            f"the package — the rule never runs")
