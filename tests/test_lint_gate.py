"""Tier-1 gate: `pio lint` must pass CLEAN over the real package.

This is the machine-checked form of the invariants that previously
lived in reviewers' heads: every finding here is either a genuine new
violation (fix it) or a deliberate exception (suppress it inline WITH a
justification — see docs/static-analysis.md). The gate runs every
registered rule with the repo policy config, exactly what
`bin/pio-lint` runs in CI.
"""

from __future__ import annotations

import pytest

from predictionio_tpu.analysis import (
    all_rules,
    default_config,
    format_findings,
    lint_package,
    lint_package_report,
)

pytestmark = pytest.mark.lint

EXPECTED_RULES = {
    "resilience-bypass",
    "jit-purity",
    "host-sync-in-hot-path",
    "dtype-discipline",
    "untimed-blocking-io",
    "lock-discipline",
    # whole-program (project) passes
    "shared-state-race",
    "lock-order",
    "jit-recompile-risk",
}

PROJECT_RULES = {"shared-state-race", "lock-order", "jit-recompile-risk"}


def test_rule_suite_is_complete():
    """The gate is only as strong as its rule set: all nine invariant
    families must be registered AND enabled in the repo policy."""
    registered = set(all_rules())
    assert EXPECTED_RULES <= registered
    enabled = set(default_config().enabled_rules())
    assert EXPECTED_RULES <= enabled


def test_package_lints_clean():
    """All rules over all of predictionio_tpu/: zero findings. A failure
    message IS the lint report — fix the violation or suppress it with
    a justification at the site."""
    findings, stats = lint_package_report()
    assert not findings, "\n" + format_findings(findings)
    # the clean verdict must come from a run where the whole-program
    # passes actually executed — a gate that silently skipped them
    # would be vacuously green
    assert set(stats.project_rules) >= PROJECT_RULES
    assert stats.files > 100


def test_recompile_scope_covers_factory_backed_entries():
    """The jit-recompile-risk scope must include the factory-backed
    sharded serving dispatch: ``recommend_topk_sharded`` is a plain
    function, but its ``k`` keys the lru-cached shard_map program in
    ``ops/topk._sharded_topk_fn`` — invisible to the decorator scan, so
    it rides the ``extra_entries`` option. Dropping it from the policy
    silently un-lints every sharded-serving call site."""
    config = default_config()
    opts = config.rules["jit-recompile-risk"].options
    assert opts.get("extra_entries", {}).get(
        "recommend_topk_sharded") == ["k"]
    assert set(opts.get("snap_calls", ())) >= {"serving_k",
                                               "serving_batch"}


def test_warm_cache_run_is_not_slower_than_module_only(tmp_path):
    """The per-file cache must make a warm full run (all nine rules,
    project passes included) no slower than the pre-cache per-module-only
    run it replaces. Loose bound: timings on shared CI boxes jitter."""
    from predictionio_tpu.analysis.cache import LintCache, rules_fingerprint

    fingerprint = rules_fingerprint(default_config())
    path = str(tmp_path / "lint-cache.json")

    cold_findings, cold = lint_package_report(
        cache=LintCache(path, fingerprint))
    assert cold.cache_misses == cold.files and cold.cache_hits == 0

    warm_findings, warm = lint_package_report(
        cache=LintCache(path, fingerprint))
    assert warm.cache_hits == warm.files and warm.cache_misses == 0
    assert warm_findings == cold_findings

    # the legacy shape this PR must not regress: per-module rules only,
    # no cache, no project passes
    _, legacy = lint_package_report(cache=None, project=False)
    assert warm.total_s <= legacy.total_s * 1.5 + 0.5, (
        f"warm cached full run ({warm.total_s:.2f}s) should not be "
        f"slower than the uncached per-module run ({legacy.total_s:.2f}s)")


def test_every_rule_actually_runs_on_the_package():
    """Guard against a rule silently scoping itself out of existence:
    each rule's configured paths must match at least one real file."""
    import os

    import predictionio_tpu

    from predictionio_tpu.analysis.config import path_matches

    pkg = os.path.dirname(predictionio_tpu.__file__)
    relpaths = [
        os.path.relpath(os.path.join(dirpath, f), pkg).replace(os.sep, "/")
        for dirpath, _, files in os.walk(pkg)
        for f in files
        if f.endswith(".py")
    ]
    config = default_config()
    for rule_id, rule in all_rules().items():
        prefixes = config.rule_paths(rule)
        assert any(path_matches(rp, prefixes) for rp in relpaths), (
            f"{rule_id}: configured paths {prefixes} match no file under "
            f"the package — the rule never runs")
