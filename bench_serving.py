"""Serving-path benchmark: QPS + latency percentiles under concurrency.

Every BENCH_*.json before PR 3 tracked only ALS *training* throughput;
this harness gives the serving hot path its own trajectory. It stands
up a real ``EngineServer`` (HTTP loopback, the production handler
stack) over a synthetic ALS model and drives it with N concurrent
clients in three configurations:

- ``per_query``  — strict one-predict-per-request dispatch
                   (``batch_policy="fixed", batch_max=1``: the
                   reference PredictionIO serving model,
                   CreateServer.scala:495-497), the baseline;
- ``adaptive``   — the PR 3 adaptive micro-batcher (EWMA wait,
                   menu-snapped batch sizes, dedup);
- ``traced``     — the adaptive configuration with request tracing ON
                   (ServerConfig.tracing; docs/observability.md): the
                   overhead pin for the PR 5 observability plane —
                   ``tracing_overhead_pct`` in the artifact must stay
                   ≤ 5%;
- ``cached``     — adaptive + the result cache, clients drawing from a
                   small hot query pool (the repeated-query regime the
                   cache exists for);
- ``router``     — the PR 6 fleet tier's overhead pin (docs/fleet.md):
                   a ``RouterServer`` fronting TWO adaptive replicas vs
                   one direct adaptive server, paired order-alternated
                   rounds, steady-state means — ``router_overhead_pct``
                   in the artifact must stay ≤ 10% qps at the default
                   client count.
- ``ann``        — the PR 8 sublinear-retrieval sweep
                   (docs/serving-performance.md): brute full-catalog
                   scoring vs the IVF-flat MIPS index + exact rescore
                   (ops/ann) at 100k and 1M items, equal client count,
                   recall@shortlist and MAP@10 vs brute measured
                   alongside (BENCH_ann_rNN.json).
- ``workers``    — the prefork serving pool's core-scaling pin
                   (``pio deploy --workers N``; BENCH_workers_rNN.json):
                   ONE adaptive engine-server process vs TWO sharing an
                   SO_REUSEPORT port (spool peering on, the production
                   shape), same model/config/client count, interleaved
                   rounds, steady-state means. On a multi-core host the
                   2-worker pool should clear ~1.6x (linear minus
                   coordination); the artifact records ``host_cores`` —
                   on a 1-core container the ratio is capacity-bound at
                   ~1.0x and measures coordination overhead only. The
                   ANN 1M HTTP phase re-runs under 2 workers to measure
                   how much of the device-level 8.7x the multi-process
                   plane recovers from the GIL floor.

Prints ONE JSON line PER PHASE GROUP in the BENCH contract
(``{"metric", "value", "unit", ...}``): the serving line (adaptive /
traced / cached, with p50/p95/p99 per phase and the
adaptive-vs-per-query speedup) followed by the router-overhead line;
``--router-only`` emits just the latter. Runs anywhere jax runs — CPU
(``JAX_PLATFORMS=cpu``) included; the batching win it measures is the
amortization of per-dispatch overhead (kernel launch + factor-table
traversal shared across the batch), which exists on every backend and
grows with the device RTT.

Also importable: ``bench.py`` wires :func:`bench_section` in as the
``serving_path`` section so the round artifacts carry these numbers.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import threading
import time

import numpy as np

DEF_ITEMS = 100_000
DEF_RANK = 32
DEF_CLIENTS = 24
DEF_PER_CLIENT = 25
DEF_WARMUP = 4
#: the uncached phases draw uniformly from this many distinct queries —
#: recommendation traffic is popularity-skewed, and a hot pool is what
#: gives the batcher's dedup pass (and the baseline, which cannot
#: exploit duplicates) the same realistic workload; the artifact
#: reports the observed dedup count alongside the pool size
DEF_POOL = 64


def host_core_ratio_caveat(min_cores: int = 2) -> str | None:
    """The bench host-core guard (memory note bench-host-cores): a
    multi-process scaling or overhead ratio measured on a host with
    fewer cores than competing processes is capacity-bound by kernel
    time-slicing, not by the code under test. Callers still REPORT the
    number (round-over-round continuity on the same host is real) but
    attach this caveat instead of treating it as a pin; None on a host
    with enough cores to make the ratio meaningful."""
    cores = os.cpu_count() or 1
    if cores >= min_cores:
        return None
    return (f"host_cores={cores}: multi-process ratio is time-slice "
            f"bound below {min_cores} cores — reported for "
            "continuity, NOT a pin")


def build_deployed(items: int = DEF_ITEMS, rank: int = DEF_RANK,
                   users: int = 2048, seed: int = 7):
    """A DeployedEngine over a synthetic ALS model (device-resident
    factors, string entity ids — the production shape, minus training)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.controller.base import FirstServing
    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.storage.base import EngineInstance
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.utils.bimap import BiMap, EntityIdIxMap
    from predictionio_tpu.workflow.deploy import DeployedEngine

    rng = np.random.default_rng(seed)
    user_f = rng.standard_normal((users, rank)).astype(np.float32)
    item_f = rng.standard_normal((items, rank)).astype(np.float32)
    seen_by_user = {
        u: rng.choice(items, size=8, replace=False).astype(np.int32)
        for u in range(users)
    }
    model = ALSModel(
        rank=rank,
        user_factors=jax.device_put(jnp.asarray(user_f)),
        item_factors=jax.device_put(jnp.asarray(item_f)),
        user_ids=EntityIdIxMap(BiMap({f"u{i}": i for i in range(users)})),
        item_ids=EntityIdIxMap(BiMap({f"i{i}": i for i in range(items)})),
        seen_by_user=seen_by_user,
    )
    algo = rec.ALSAlgorithm(rec.ALSAlgorithmParams(rank=rank, use_mesh=False))
    now = datetime.datetime.now(datetime.timezone.utc)
    instance = EngineInstance(
        id="bench-serving", status="COMPLETED", start_time=now,
        completion_time=now, engine_id="bench-serving", engine_version="1",
        engine_variant="bench-serving", engine_factory="bench-serving",
    )
    return DeployedEngine(None, instance, [algo], FirstServing(), [model])


def warm_batch_signatures(deployed, batch_max: int) -> None:
    """Pre-compile every padded batch signature the coalescer can
    produce (the power-of-two menu): a signature first seen inside the
    timed loop would bill a jit compile as serving time."""
    from predictionio_tpu.ops.topk import BATCH_WIDTHS
    from predictionio_tpu.templates import recommendation as rec

    for b in BATCH_WIDTHS:
        if b > max(batch_max, 1):
            break
        deployed.query_batch(
            [rec.Query(user=f"u{j}", num=10) for j in range(b)])


#: client processes the load splits across — IN-PROCESS client threads
#: share the server's GIL and collapse the measurement (24 in-process
#: clients drove pure-HTTP throughput from ~570 to ~105 req/s on this
#: 2-core host purely from GIL convoy); ONE separate process keeps the
#: server's interpreter lock free without starving a small host's
#: cores (3 measured best on this 2-core host once the server's
#: buffered-write/NODELAY response path landed; tune via
#: --client-procs)
DEF_CLIENT_PROCS = 3


def _client_main(argv: list[str]) -> None:
    """Load-generator subprocess: ``--threads`` keep-alive connections
    fire ``--count`` queries each after a GO handshake on stdin (so all
    processes start together and startup cost stays out of the timed
    window); per-request latencies go back as one JSON line."""
    import argparse
    import sys

    sys.setswitchinterval(0.0005)
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--threads", type=int, required=True)
    ap.add_argument("--count", type=int, required=True)
    ap.add_argument("--warmup", type=int, default=DEF_WARMUP)
    ap.add_argument("--cid0", type=int, default=0,
                    help="first global client id (seeds each client's "
                         "independent RNG over the shared pool)")
    ap.add_argument("--pool-size", type=int, required=True)
    ap.add_argument("--path", default="/queries.json",
                    help="request target (the gateway phase drives "
                         "/engines/<name>/queries.json per tenant)")
    ap.add_argument("--throttle-backoff", action="store_true",
                    help="honor 429 Retry-After hints (sleep the hint "
                         "before retrying) — the COMPLIANT over-quota "
                         "tenant; without it the client hammers, the "
                         "abusive one")
    args = ap.parse_args(argv)

    import random
    import socket

    # wrk-style raw-socket clients: full request bytes pre-built per
    # pool entry, responses parsed with a minimal Content-Length
    # scanner. http.client costs ~2ms of CPU per request (header
    # assembly + email-parser response headers), and on a small host
    # the load generator's CPU comes out of the server's budget —
    # a benchmark client must be cheaper than the thing it measures.
    requests = []
    target = args.path.encode()
    for i in range(args.pool_size):
        body = json.dumps({"user": f"u{i}", "num": 10}).encode()
        requests.append(
            b"POST " + target + b" HTTP/1.1\r\n"
            b"Host: 127.0.0.1\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"\r\n" + body)
    lat: list[list[float]] = [[] for _ in range(args.threads)]
    errors = [0] * args.threads
    statuses: list[dict[int, int]] = [{} for _ in range(args.threads)]

    def read_response(sock: socket.socket,
                      buf: bytearray) -> tuple[int, float | None]:
        # headers, then exactly Content-Length body bytes (the server
        # always sends Content-Length — engine_server._respond);
        # returns (status, retry_after_hint) so the gateway phase can
        # count a quota-throttled tenant's 429s apart from served 200s
        # and honor the hint under --throttle-backoff
        while True:
            head_end = buf.find(b"\r\n\r\n")
            if head_end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("closed mid-headers")
            buf += chunk
        head = bytes(buf[:head_end]).lower()
        status = int(head[9:12])        # b"http/1.1 NNN ..."
        retry_after = None
        if status == 429:               # off the 200 path entirely
            at_ra = head.find(b"retry-after:")
            if at_ra >= 0:
                end_ra = head.find(b"\r\n", at_ra)
                try:
                    retry_after = float(
                        head[at_ra + 12:end_ra if end_ra >= 0 else None])
                except ValueError:
                    retry_after = None
        marker = b"content-length:"
        at = head.find(marker)
        if at < 0:
            raise ConnectionError("no content-length")
        line_end = head.find(b"\r\n", at)
        if line_end < 0:
            line_end = len(head)   # Content-Length was the last header
        length = int(head[at + len(marker):line_end])
        need = head_end + 4 + length
        while len(buf) < need:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("closed mid-body")
            buf += chunk
        del buf[:need]
        return status, retry_after

    def client(tid: int, count: int, record: bool) -> None:
        cid = args.cid0 + tid
        # uniform draws over the shared pool (seeded per client):
        # deterministic striding would minimize concurrent duplicates
        # and understate what a popularity-skewed workload hands the
        # dedup pass
        rng = random.Random(1000 + cid)
        sock: socket.socket | None = None
        buf = bytearray()
        try:
            for j in range(count):
                req = requests[rng.randrange(args.pool_size)]
                t0 = time.perf_counter()
                try:
                    if sock is None:
                        sock = socket.create_connection(
                            ("127.0.0.1", args.port), timeout=120)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        buf.clear()
                    sock.sendall(req)
                    status, retry_after = read_response(sock, buf)
                except OSError:
                    errors[tid] += 1
                    if sock is not None:
                        sock.close()
                    sock = None        # reconnects on next request
                    continue
                if record:
                    statuses[tid][status] = \
                        statuses[tid].get(status, 0) + 1
                    # only SERVED requests feed the latency
                    # distribution: a 429 answers in microseconds and
                    # would flatter a throttled tenant's percentiles
                    if status == 200:
                        lat[tid].append(time.perf_counter() - t0)
                if status == 429 and args.throttle_backoff:
                    time.sleep(min(retry_after or 0.05, 1.0))
        finally:
            if sock is not None:
                sock.close()

    def run(count: int, record: bool) -> None:
        threads = [
            threading.Thread(target=client, args=(t, count, record))
            for t in range(args.threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    run(args.warmup, record=False)
    print("READY", flush=True)
    sys.stdin.readline()            # GO
    run(args.count, record=True)
    merged_status: dict[int, int] = {}
    for per in statuses:
        for code, n in per.items():
            merged_status[code] = merged_status.get(code, 0) + n
    print(json.dumps({
        "lat": [x for per in lat for x in per],
        "errors": int(sum(errors)),
        "status": {str(k): v for k, v in sorted(merged_status.items())},
    }), flush=True)


def _spawn_client(port: int, threads: int, count: int, warmup: int,
                  cid0: int, pool_size: int,
                  path: str = "/queries.json", backoff: bool = False):
    """One load-generator child on the shared _client_main protocol
    (READY after warmup → GO on stdin → one JSON result line) — the
    ONE place the child argv is assembled, shared by every phase."""
    import subprocess
    import sys

    return subprocess.Popen(
        [sys.executable, __file__, "--client",
         "--port", str(port), "--threads", str(threads),
         "--count", str(count), "--warmup", str(warmup),
         "--cid0", str(cid0), "--pool-size", str(pool_size),
         "--path", path,
         *(["--throttle-backoff"] if backoff else [])],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)


def _go(children: list) -> tuple[list[dict], float]:
    """READY-handshake every child, broadcast GO, collect each child's
    result line; returns (outputs, wall seconds of the timed window)."""
    for child in children:
        assert child.stdout.readline().strip() == "READY"
    t0 = time.perf_counter()
    for child in children:
        child.stdin.write("GO\n")
        child.stdin.flush()
    outs = [json.loads(child.stdout.readline()) for child in children]
    dt = time.perf_counter() - t0
    for child in children:
        child.wait(timeout=30)
    return outs, dt


def _run_round(port: int | list[int], pool_size: int, clients: int,
               per_client: int, warmup: int, procs: int) -> dict:
    """One synchronized multi-process load round against ``port`` — or
    several ports: a LIST splits the client processes round-robin
    across them (client-side load balancing, the router bench's
    direct-to-replicas baseline)."""
    ports = [port] if isinstance(port, int) else list(port)
    procs = max(len(ports), min(procs, clients))
    per_proc = [clients // procs + (1 if i < clients % procs else 0)
                for i in range(procs)]
    children = []
    cid0 = 0
    for i, n_threads in enumerate(per_proc):
        children.append(_spawn_client(
            ports[i % len(ports)], n_threads, per_client, warmup,
            cid0, pool_size))
        cid0 += n_threads
    outs, dt = _go(children)
    flat = np.asarray([x for o in outs for x in o["lat"]])
    done = int(flat.size)
    status_counts: dict[str, int] = {}
    for o in outs:
        for code, n in (o.get("status") or {}).items():
            status_counts[code] = status_counts.get(code, 0) + n
    return {
        "qps": round(done / dt, 1),
        "p50_ms": (round(float(np.percentile(flat, 50)) * 1e3, 2)
                   if done else None),
        "p95_ms": (round(float(np.percentile(flat, 95)) * 1e3, 2)
                   if done else None),
        "p99_ms": (round(float(np.percentile(flat, 99)) * 1e3, 2)
                   if done else None),
        "queries": done,
        "errors": int(sum(o["errors"] for o in outs)),
        "status_counts": status_counts,
    }


def _drive(port: int | list[int], user_pool: list[str], clients: int,
           per_client: int,
           warmup: int = DEF_WARMUP, rounds: int = 2,
           procs: int = DEF_CLIENT_PROCS) -> dict:
    """N keep-alive clients (split over separate processes), M queries
    each, best of ``rounds`` synchronized rounds — the 2-core host's
    load shifts swing single-round QPS, and the best round is the
    least-interfered measurement of the same code (bench.py's min-of-N
    discipline). Every client draws uniformly from the SHARED hot pool
    (_client_main) — concurrent duplicates are part of the workload,
    and the adaptive phase's dedup pass exploiting them while the
    per-query baseline cannot is part of what the ratio measures."""
    best = None
    for _ in range(rounds):
        candidate = _run_round(port, len(user_pool), clients, per_client,
                               warmup, procs)
        if best is None or candidate["qps"] > best["qps"]:
            best = candidate
    return best


def _steady_mean(round_qps: list[float]) -> float:
    """Mean qps over the steady-state rounds: the first round is
    dropped when more than two ran (it carries the fleet's cold
    costs; see the tracing_overhead_pct comment)."""
    steady = round_qps[1:] if len(round_qps) > 2 else round_qps
    return sum(steady) / len(steady)


def _stats_doc(port: int) -> dict:
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats.json", timeout=10) as r:
        return json.loads(r.read())


def bench_serving(items: int = DEF_ITEMS, rank: int = DEF_RANK,
                  clients: int = DEF_CLIENTS,
                  per_client: int = DEF_PER_CLIENT,
                  batch_max: int = 32, hot_pool: int = 32,
                  rounds: int = 4, procs: int = DEF_CLIENT_PROCS) -> dict:
    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.workflow.deploy import ServerConfig

    deployed = build_deployed(items=items, rank=rank)
    warm_batch_signatures(deployed, batch_max)
    users = len(deployed.models[0].user_ids)
    pool = [f"u{i}" for i in range(min(users, DEF_POOL))]

    # per_query (strict one-predict-per-request, the reference serving
    # model), adaptive, and traced (adaptive + request tracing, the
    # observability-plane overhead pin) run INTERLEAVED, best round per
    # config: the host's load drifts minute to minute, and the
    # headlines are their RATIOS — alternating rounds sample comparable
    # conditions (the same reasoning as bench.py's interleaved
    # _chain_time_many)
    base_server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        batch_policy="fixed", batch_max=1, batch_wait_ms=0.0))
    adapt_server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        batch_policy="adaptive", batch_max=batch_max, batch_wait_ms=5.0))
    traced_server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        batch_policy="adaptive", batch_max=batch_max, batch_wait_ms=5.0,
        tracing=True))
    base_server.start()
    adapt_server.start()
    traced_server.start()
    base = adaptive = traced = None
    adaptive_rounds: list[float] = []
    traced_rounds: list[float] = []
    try:
        for i in range(rounds):
            # adaptive and traced ALTERNATE order round to round: the
            # overhead number is a small DIFFERENCE, and a fixed
            # position inside the round cycle would fold the host's
            # within-cycle drift into it
            b = _drive(base_server.port, pool, clients, per_client,
                       rounds=1, procs=procs)
            pair = [(adapt_server, "a"), (traced_server, "t")]
            if i % 2:
                pair.reverse()
            for server, tag in pair:
                r = _drive(server.port, pool, clients, per_client,
                           rounds=1, procs=procs)
                if tag == "a":
                    adaptive_rounds.append(r["qps"])
                    if adaptive is None or r["qps"] > adaptive["qps"]:
                        adaptive = r
                else:
                    traced_rounds.append(r["qps"])
                    if traced is None or r["qps"] > traced["qps"]:
                        traced = r
            if base is None or b["qps"] > base["qps"]:
                base = b
        astats = _stats_doc(adapt_server.port)
    finally:
        base_server.stop()
        adapt_server.stop()
        traced_server.stop()

    # repeated-query regime: adaptive + result cache over a hot pool
    cache_server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        batch_policy="adaptive", batch_max=batch_max, batch_wait_ms=5.0,
        cache_enabled=True, cache_ttl_s=300.0))
    cache_server.start()
    try:
        cached = _drive(cache_server.port, pool[:hot_pool], clients,
                        per_client, rounds=rounds, procs=procs)
        cstats = _stats_doc(cache_server.port)
    finally:
        cache_server.stop()

    out = {
        "metric": f"serving_qps_adaptive_{clients}c",
        "value": adaptive["qps"],
        "unit": "qps",
        "p50_ms": adaptive["p50_ms"],
        "p95_ms": adaptive["p95_ms"],
        "p99_ms": adaptive["p99_ms"],
        "per_query_qps": base["qps"],
        "per_query_p50_ms": base["p50_ms"],
        "per_query_p99_ms": base["p99_ms"],
        "speedup_vs_per_query_x": round(
            adaptive["qps"] / base["qps"], 2) if base["qps"] else None,
        # observability-plane overhead pin (docs/observability.md):
        # adaptive qps with per-request tracing ON vs OFF. The
        # overhead is a small DIFFERENCE, so it compares MEANS over
        # the order-alternated paired rounds — a best-of-N vs
        # best-of-N ratio amplifies the asymmetry of two noisy maxima
        # and misreports session drift as tracing cost (measured: the
        # same code read 1% paired-mean and 6% best-of on one
        # session). The FIRST paired round is excluded when more than
        # two ran: it absorbs the fleet's cold costs (thread spawn,
        # page cache, allocator growth — measured 3x below steady
        # state) and lands them on whichever phase ran first.
        # Negative = noise swamped the cost.
        "traced_qps": traced["qps"],
        "traced_p50_ms": traced["p50_ms"],
        "tracing_overhead_pct": round(
            (1.0 - _steady_mean(traced_rounds)
             / _steady_mean(adaptive_rounds)) * 100.0, 2),
        "adaptive_round_qps": adaptive_rounds,
        "traced_round_qps": traced_rounds,
        "cached_qps": cached["qps"],
        "cached_p50_ms": cached["p50_ms"],
        "cache_hit_ratio": cstats["serving"]["cacheHitRatio"],
        "clients": clients,
        "queries_per_phase": adaptive["queries"],
        "errors": base["errors"] + adaptive["errors"] + cached["errors"],
        "batch_size_histogram": astats["serving"]["batchSizeHistogram"],
        "ewma_interarrival_ms": astats["batching"]["ewmaInterarrivalMs"],
        "deduped": astats["serving"]["deduped"],
        "items": items,
        "rank": rank,
    }
    return out


def _replica_main(argv: list[str]) -> None:
    """Replica subprocess: synthetic adaptive engine server, the same
    production shape the serving phases measure — in its OWN process
    so the router never steals interpreter time from the model server
    (the GIL-convoy lesson of the in-process client experiment)."""
    import argparse
    import sys

    sys.setswitchinterval(0.0005)
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--batch-max", type=int, required=True)
    args = ap.parse_args(argv)

    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.workflow.deploy import ServerConfig

    deployed = build_deployed(items=args.items, rank=args.rank)
    warm_batch_signatures(deployed, args.batch_max)
    server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=0, batching=True,
        batch_policy="adaptive", batch_max=args.batch_max,
        batch_wait_ms=5.0))
    server.start()
    print(f"PORT {server.port}", flush=True)
    sys.stdin.readline()                 # parent closes stdin to stop
    server.stop()


def _serving_worker_main(argv: list[str]) -> None:
    """One `pio deploy --workers N` sibling for the workers phase: a
    synthetic adaptive engine server on the SHARED SO_REUSEPORT port
    with spool peering on — the production worker-pool shape, in its
    own process so the GIL boundary is real. ``--model-dir`` loads a
    persisted ALSModel (the ANN-under-workers phase shares ONE built
    index across siblings through the checkpoint instead of paying
    k-means per worker) and ``--retrieval ann`` serves through it."""
    import argparse
    import sys

    sys.setswitchinterval(0.0005)
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=int, default=DEF_ITEMS)
    ap.add_argument("--rank", type=int, default=DEF_RANK)
    ap.add_argument("--batch-max", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--spool", default=None)
    ap.add_argument("--model-dir", default=None)
    ap.add_argument("--retrieval", default="brute")
    ap.add_argument("--nprobe", type=int, default=0)
    # the shm phase's two arms: --cache alone is the replicated
    # private-LRU baseline, --cache --shm-segment NAME attaches every
    # sibling to one pre-created seqlock segment (shm_cache.py)
    ap.add_argument("--cache", action="store_true")
    ap.add_argument("--shm-segment", default="")
    args = ap.parse_args(argv)

    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.workflow.deploy import ServerConfig

    if args.model_dir:
        from predictionio_tpu.models.als import ALSModel

        model = ALSModel.load(args.model_dir)
        model.configure_retrieval(args.retrieval, nprobe=args.nprobe)
        deployed = _deployed_from_model(model)
    else:
        deployed = build_deployed(items=args.items, rank=args.rank)
    warm_batch_signatures(deployed, args.batch_max)
    deployed.query(rec.Query(user="u0", num=10))     # compile B=1
    server = EngineServer(deployed, ServerConfig(
        ip="127.0.0.1", port=args.port, batching=True,
        batch_policy="adaptive", batch_max=args.batch_max,
        batch_wait_ms=5.0,
        reuse_port=True, worker_spool_dir=args.spool,
        admin_sync_interval_s=0.5,
        cache_enabled=args.cache or bool(args.shm_segment),
        # rounds span minutes; a 30s TTL would turn the steady-state
        # hit ratio into a TTL-expiry measurement
        cache_ttl_s=300.0,
        shm_cache=bool(args.shm_segment),
        shm_segment=args.shm_segment))
    server.start()
    print(f"PORT {server.port}", flush=True)
    sys.stdin.readline()                 # parent closes stdin to stop
    server.stop()


def _spawn_worker_pool(n: int, extra_args: list[str]):
    """(children, shared_port, spool_dir): n serving-worker processes
    on one SO_REUSEPORT port with a fresh peering spool."""
    import tempfile

    from predictionio_tpu.cli.pio import resolve_concrete_port

    port = resolve_concrete_port("127.0.0.1", 0)
    spool = tempfile.mkdtemp(prefix="pio-bench-workers-")
    children = []
    try:
        for _ in range(n):
            children.append(_spawn("serving-worker", [
                "--port", str(port), "--spool", spool, *extra_args])[0])
    except Exception:
        import shutil

        for proc in children:
            proc.kill()
        # callers only clean spools from SUCCESSFUL calls
        shutil.rmtree(spool, ignore_errors=True)
        raise
    return children, port, spool


def _stop_children(children) -> None:
    for proc in children:
        try:
            if proc.stdin and not proc.stdin.closed:
                proc.stdin.close()
            proc.wait(timeout=15)
        except Exception:
            proc.kill()


def bench_workers(items: int = DEF_ITEMS, rank: int = DEF_RANK,
                  clients: int = DEF_CLIENTS,
                  per_client: int = DEF_PER_CLIENT,
                  batch_max: int = 32, rounds: int = 6,
                  procs: int = DEF_CLIENT_PROCS,
                  ann_items: int | None = 1_000_000,
                  ann_per_client: int = 16,
                  ann_rounds: int = 2) -> dict:
    """The prefork pool's core-scaling phase (module docstring): the
    SAME synthetic adaptive workload served by 1 process vs 2
    SO_REUSEPORT siblings, paired order-alternated rounds, steady-state
    means with the first paired round dropped — the router-overhead
    measurement discipline. ``ann_items`` additionally re-runs the
    PR 8 ANN-vs-brute HTTP ratio with both modes under 2 workers (one
    index built once, shared through a checkpoint; None skips it)."""
    import os

    worker_args = ["--items", str(items), "--rank", str(rank),
                   "--batch-max", str(batch_max)]
    pool = [f"u{i}" for i in range(DEF_POOL)]
    one_rounds: list[float] = []
    two_rounds: list[float] = []
    one_best = two_best = None
    single: list = []
    duo: list = []
    spool = spool1 = None
    workers_reported = None
    try:
        single, single_port, spool1 = _spawn_worker_pool(1, worker_args)
        duo, duo_port, spool = _spawn_worker_pool(2, worker_args)
        for i in range(rounds):
            pair = [(single_port, "1"), (duo_port, "2")]
            if i % 2:
                pair.reverse()
            for port, tag in pair:
                r = _drive(port, pool, clients, per_client,
                           rounds=1, procs=procs)
                if tag == "1":
                    one_rounds.append(r["qps"])
                    if one_best is None or r["qps"] > one_best["qps"]:
                        one_best = r
                else:
                    two_rounds.append(r["qps"])
                    if two_best is None or r["qps"] > two_best["qps"]:
                        two_best = r
        # merged-scrape sanity: wherever the connection lands, the
        # exposition must fold BOTH workers in (the tentpole invariant)
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{duo_port}/metrics", timeout=10) as r:
            for line in r.read().decode().splitlines():
                if line.startswith("pio_serving_workers"):
                    workers_reported = float(line.split()[-1])
    finally:
        _stop_children(single + duo)
        import shutil

        for d in (spool1, spool):
            if d:
                shutil.rmtree(d, ignore_errors=True)

    out = {
        "metric": f"workers_scaling_2w_vs_1w_{clients}c",
        "value": round(_steady_mean(two_rounds)
                       / _steady_mean(one_rounds), 2),
        "unit": "x",
        "host_cores": os.cpu_count(),
        "host_cores_caveat": host_core_ratio_caveat(),
        "qps_1w": one_best["qps"],
        "qps_2w": two_best["qps"],
        "p50_ms_1w": one_best["p50_ms"],
        "p50_ms_2w": two_best["p50_ms"],
        "p99_ms_1w": one_best["p99_ms"],
        "p99_ms_2w": two_best["p99_ms"],
        "round_qps_1w": one_rounds,
        "round_qps_2w": two_rounds,
        "workers_reported_in_merged_metrics": workers_reported,
        "errors": one_best["errors"] + two_best["errors"],
        "clients": clients,
        "items": items,
        "rank": rank,
    }
    if ann_items:
        out["ann_http_per_workers"] = _bench_workers_ann(
            ann_items, rank, clients, ann_per_client, batch_max,
            ann_rounds, procs)
    return out


def _bench_workers_ann(items: int, rank: int, clients: int,
                       per_client: int, batch_max: int, rounds: int,
                       procs: int,
                       worker_counts: tuple = (1, 2)) -> dict:
    """The ANN satellite: the PR 8 1M-item HTTP phase re-run with BOTH
    retrieval modes behind 1 AND 2 SO_REUSEPORT workers. The original
    single-process measurement compressed the device-level ratio to ~5x
    because one Python process saturated the host; the per-workers
    sweep isolates what the prefork pool changes ON THE SAME HOST: ANN
    (host-bound, ~2ms device time per query) gains from a second
    request-handling process, while brute (device-bound, alive only on
    batch amortization of its full-table scan) LOSES — two workers
    fragment the concurrent batch into two half-size dispatches, each
    paying a full table traversal. The index is built ONCE and shared
    with every sibling through the persisted checkpoint
    (ALSModel.save/load — also the --model-mmap page-sharing path)."""
    import shutil
    import tempfile
    import time as _time

    from predictionio_tpu.ops import ann as ann_ops

    _, ann_model, item_f, _user_f = _ann_models(
        items, rank, DEF_ANN_CLUSTERS)
    t0 = _time.perf_counter()
    index = ann_ops.build_index(item_f, seed=0)
    build_s = round(_time.perf_counter() - t0, 1)
    nprobe = index.clamp_nprobe(0)
    ann_model.ann_index = index
    model_dir = tempfile.mkdtemp(prefix="pio-bench-workers-ann-")
    # the index is already on the model: save persists it as-is (no
    # second k-means); siblings load the ready checkpoint
    ann_model.save(model_dir)
    pool = [f"u{i}" for i in range(DEF_POOL)]
    base_args = ["--batch-max", str(batch_max), "--model-dir", model_dir]
    per_workers = []
    try:
        for n_workers in worker_counts:
            results: dict[str, dict] = {}
            for tag, extra in (("brute", ["--retrieval", "brute"]),
                               ("ann", ["--retrieval", "ann",
                                        "--nprobe", str(nprobe)])):
                children, port, spool = _spawn_worker_pool(
                    n_workers, base_args + extra)
                try:
                    results[tag] = _drive(port, pool, clients,
                                          per_client, rounds=rounds,
                                          procs=procs)
                finally:
                    _stop_children(children)
                    shutil.rmtree(spool, ignore_errors=True)
            brute, ann = results["brute"], results["ann"]
            per_workers.append({
                "workers": n_workers,
                "brute_qps": brute["qps"],
                "brute_p99_ms": brute["p99_ms"],
                "ann_qps": ann["qps"],
                "ann_p99_ms": ann["p99_ms"],
                "speedup_x": round(ann["qps"] / brute["qps"], 2)
                if brute["qps"] else None,
                "p99_ratio_x": round(brute["p99_ms"] / ann["p99_ms"], 2)
                if ann["p99_ms"] else None,
                "errors": brute["errors"] + ann["errors"],
            })
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
    return {
        "items": items,
        "nlist": index.nlist,
        "served_nprobe": nprobe,
        "build_s": build_s,
        "clients": clients,
        "per_workers": per_workers,
    }


def bench_workers_section(shrunk: bool = False) -> dict:
    """The ``workers_scaling`` section for bench.py's round artifact:
    the core-scaling phase only — the 1M ANN-under-workers re-run is
    the STANDALONE harness's job (``--workers-only``, minutes of index
    build; committed as BENCH_workers_rNN.json) and is skipped here at
    both sizes. ``shrunk`` (--skip-heavy) additionally shrinks the
    catalog and round count."""
    if shrunk:
        r = bench_workers(items=16_384, per_client=8, rounds=2,
                          ann_items=None)
    else:
        r = bench_workers(per_client=16, rounds=4, ann_items=None)
    return {
        "workers_scaling_2w_vs_1w_x": r["value"],
        "workers_qps_1w": r["qps_1w"],
        "workers_qps_2w": r["qps_2w"],
        "workers_host_cores": r["host_cores"],
        "workers_host_cores_caveat": r["host_cores_caveat"],
        "workers_reported_in_merged_metrics":
            r["workers_reported_in_merged_metrics"],
    }


def _scrape_counters(port: int, names: tuple) -> dict[str, float]:
    """Pool-wide counter totals from the merged /metrics exposition:
    wherever the connection lands, the scrape folds every sibling in
    (obs/registry merge_sources), so these are the POOL's numbers —
    /stats.json's serving section is per-worker and would under-count
    a 2-worker arm by whatever the other sibling served."""
    import urllib.request

    totals = {n: 0.0 for n in names}
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        for line in r.read().decode().splitlines():
            head = line.split("{")[0].split(" ")[0]
            if head in totals:
                totals[head] += float(line.split()[-1])
    return totals


def _probe_query(port: int, doc: dict) -> None:
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()


def _rewarm_misses(port: int, keys: int, passes: int = 3,
                   settle_s: float = 0.0) -> int:
    """The cold-start-elimination probe (ISSUE PR 18): invalidate the
    whole pool (POST /retrieval reconfig — every applying worker bumps
    its cache generation, private or shared), wait for sibling
    admin-sync to settle, then replay ``keys`` distinct queries
    ``passes`` times over fresh connections (SO_REUSEPORT spreads them
    across siblings) and count pool-wide misses. A shared segment pays
    exactly ``keys`` misses — the first toucher warms EVERY sibling;
    replicated private LRUs pay ~``keys`` per DISTINCT sibling the
    replays land on."""
    base = _scrape_counters(
        port, ("pio_serving_cache_misses_total",))
    _probe_reconfig(port)
    if settle_s:
        time.sleep(settle_s)
    for _ in range(passes):
        for i in range(keys):
            _probe_query(port, {"user": "u0", "num": 3 + i})
    after = _scrape_counters(
        port, ("pio_serving_cache_misses_total",))
    return int(after["pio_serving_cache_misses_total"]
               - base["pio_serving_cache_misses_total"])


def _probe_reconfig(port: int) -> None:
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/retrieval",
        data=json.dumps({"retrieval": "brute"}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()


def bench_shm(items: int = DEF_ITEMS, rank: int = DEF_RANK,
              clients: int = DEF_CLIENTS,
              per_client: int = DEF_PER_CLIENT,
              batch_max: int = 32, rounds: int = 4,
              procs: int = DEF_CLIENT_PROCS,
              rewarm_keys: int = 16,
              worker_counts: tuple = (1, 2)) -> dict:
    """The shared-memory serving-plane phase (PR 18;
    docs/serving-performance.md "Shared-memory serving plane"): the
    SAME cached workload served by a pool whose result cache is (a)
    one private LRU per worker — the replicated baseline, N physical
    copies of every hot answer — vs (b) ONE seqlock shm segment every
    sibling attaches to (`pio deploy --shm-cache`). Paired
    order-alternated rounds at each worker count give the steady-state
    qps comparison (the seqlock read path vs a plain dict is the
    overhead question); the pool-wide hit ratio and the post-
    invalidation rewarm probe are the coherence story — one warm pass
    heats EVERY sibling of a shared pool, while private caches pay the
    miss once per worker the traffic lands on."""
    import os
    import shutil

    from predictionio_tpu.serving.shm_cache import ShmResultCache

    worker_args = ["--items", str(items), "--rank", str(rank),
                   "--batch-max", str(batch_max)]
    pool = [f"u{i}" for i in range(DEF_POOL)]
    per_workers = []
    ratio_2w = None
    for n_workers in worker_counts:
        segment = f"pio-bench-shm-{os.getpid()}-{n_workers}w"
        # the bench parent is the segment owner — exactly the deploy
        # parent's role in cli_commands._deploy_pool
        owner = ShmResultCache(segment, nslots=4096, slot_bytes=4096,
                               ttl_s=300.0, create="create")
        arms: dict[str, dict] = {}
        children_all: list = []
        spools: list = []
        try:
            for tag, extra in (
                    ("private", ["--cache"]),
                    ("shm", ["--cache", "--shm-segment", segment])):
                children, port, spool = _spawn_worker_pool(
                    n_workers, worker_args + extra)
                children_all += children
                spools.append(spool)
                arms[tag] = {"port": port, "rounds": [], "best": None}
            for i in range(rounds):
                order = ["private", "shm"]
                if i % 2:
                    order.reverse()
                for tag in order:
                    r = _drive(arms[tag]["port"], pool, clients,
                               per_client, rounds=1, procs=procs)
                    arms[tag]["rounds"].append(r["qps"])
                    best = arms[tag]["best"]
                    if best is None or r["qps"] > best["qps"]:
                        arms[tag]["best"] = r
            for tag in ("private", "shm"):
                c = _scrape_counters(arms[tag]["port"], (
                    "pio_serving_cache_hits_total",
                    "pio_serving_cache_misses_total"))
                hits = c["pio_serving_cache_hits_total"]
                misses = c["pio_serving_cache_misses_total"]
                arms[tag]["hit_ratio"] = (
                    round(hits / (hits + misses), 4)
                    if hits + misses else None)
                # sibling sync applies the reconfig once per worker
                # (~admin_sync_interval_s apart); probing before the
                # last sibling's generation bump would re-chill keys
                # warmed by pass 1 and measure the race, not the cache
                arms[tag]["rewarm_misses"] = _rewarm_misses(
                    arms[tag]["port"], rewarm_keys,
                    settle_s=2.0 if n_workers > 1 else 0.0)
        finally:
            _stop_children(children_all)
            for d in spools:
                shutil.rmtree(d, ignore_errors=True)
            owner.close(unlink=True)
        entry = {"workers": n_workers}
        for tag in ("private", "shm"):
            a = arms[tag]
            entry[f"{tag}_qps"] = a["best"]["qps"]
            entry[f"{tag}_p99_ms"] = a["best"]["p99_ms"]
            entry[f"{tag}_steady_qps"] = round(
                _steady_mean(a["rounds"]), 1)
            entry[f"{tag}_round_qps"] = a["rounds"]
            entry[f"{tag}_hit_ratio"] = a["hit_ratio"]
            entry[f"{tag}_rewarm_misses"] = a["rewarm_misses"]
            entry[f"{tag}_errors"] = a["best"]["errors"]
        entry["shm_vs_private_x"] = (
            round(entry["shm_steady_qps"] / entry["private_steady_qps"],
                  2) if entry["private_steady_qps"] else None)
        if n_workers == 2:
            ratio_2w = entry["shm_vs_private_x"]
        per_workers.append(entry)
    return {
        "metric": f"shm_cache_2w_shm_vs_private_{clients}c",
        "value": ratio_2w,
        "unit": "x",
        "host_cores": os.cpu_count(),
        "host_cores_caveat": host_core_ratio_caveat(),
        "rewarm_keys": rewarm_keys,
        "per_workers": per_workers,
        "clients": clients,
        "items": items,
        "rank": rank,
    }


def bench_shm_section(shrunk: bool = False) -> dict:
    """The ``shm_cache`` section for bench.py's round artifact:
    paired private-vs-shm at 1 and 2 workers. ``shrunk``
    (--skip-heavy) shrinks the catalog, round count, and probe size;
    the key set is pinned by tests/test_bench_contract.py."""
    if shrunk:
        r = bench_shm(items=16_384, per_client=8, rounds=2,
                      rewarm_keys=8)
    else:
        r = bench_shm(per_client=16)
    by_workers = {e["workers"]: e for e in r["per_workers"]}
    out: dict = {}
    for n in (1, 2):
        e = by_workers[n]
        out[f"shm_qps_{n}w_private"] = e["private_qps"]
        out[f"shm_qps_{n}w_shm"] = e["shm_qps"]
    for tag in ("private", "shm"):
        out[f"shm_hit_ratio_2w_{tag}"] = by_workers[2][f"{tag}_hit_ratio"]
        out[f"shm_rewarm_misses_2w_{tag}"] = \
            by_workers[2][f"{tag}_rewarm_misses"]
        out[f"shm_p99_ms_2w_{tag}"] = by_workers[2][f"{tag}_p99_ms"]
    out["shm_host_cores"] = r["host_cores"]
    out["shm_host_cores_caveat"] = r["host_cores_caveat"]
    return out


def _router_main(argv: list[str]) -> None:
    """Router worker subprocess (how `pio router` deploys: its own
    process; ``--workers N`` spawns N of these sharing one
    SO_REUSEPORT listen port)."""
    import argparse
    import sys

    sys.setswitchinterval(0.0005)
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", action="append", default=None)
    ap.add_argument("--engine", action="append", default=None,
                    help="gateway phase: name=rec,backend=h:p[,qps=N]"
                         " (fleet/gateway.py flag grammar)")
    ap.add_argument("--default-engine", default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--reuse-port", action="store_true")
    args = ap.parse_args(argv)

    from predictionio_tpu.api.router_server import RouterServer
    from predictionio_tpu.fleet.gateway import (
        EngineSpec,
        parse_engine_flag,
    )
    from predictionio_tpu.fleet.router import RouterConfig

    engines = ()
    if args.engine:
        engines = tuple(
            EngineSpec(name=f["name"], backends=f["backends"],
                       quota_qps=f["qps"], quota_burst=f["burst"],
                       max_inflight=f["max_inflight"])
            for f in (parse_engine_flag(t) for t in args.engine))
    # generous probe budget: a GIL-saturated CPython replica can sit on
    # a /healthz answer for over a second at full load, and a bench
    # round that marks a healthy-but-busy replica down measures the
    # mark-down, not the router hop
    server = RouterServer(RouterConfig(
        ip="127.0.0.1", port=args.port,
        backends=tuple(args.backend or ()),
        engines=engines,
        **({"default_engine": args.default_engine}
           if args.default_engine else {}),
        reuse_port=args.reuse_port,
        probe_timeout_s=5.0, down_after=3))
    server.start()
    print(f"PORT {server.port}", flush=True)
    sys.stdin.readline()
    if engines:
        stats = {"per_engine": {
            g.name: g.router.stats.raw_counts()
            for g in server.gateway.groups()}}
    else:
        stats = server.router.stats.raw_counts()
    server.stop()
    print(json.dumps(stats), flush=True)


def _spawn(mode: str, argv: list[str]):
    """(process, announced port) for a --replica/--router child."""
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, __file__, f"--{mode}", *argv],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    if not line.startswith("PORT "):
        proc.kill()
        raise AssertionError(f"{mode} child said {line!r}")
    return proc, int(line.split()[1])


def bench_router(items: int = DEF_ITEMS, rank: int = DEF_RANK,
                 clients: int = DEF_CLIENTS,
                 per_client: int = 50,
                 batch_max: int = 32, rounds: int = 6,
                 procs: int = DEF_CLIENT_PROCS) -> dict:
    """The fleet router's cost, pinned the way tracing's was
    (docs/fleet.md): the SAME two replica processes driven two ways —
    ``direct`` (client processes split across the replicas: client-side
    round-robin, the no-router fleet) vs ``router`` (every client
    through one router process). Same fleet, same model, same batching
    regime; the ONLY difference is the router hop, so the ratio is the
    router's cost and nothing else. Every server runs in its OWN
    process exactly as `pio deploy`/`pio router` deploy them (an
    in-process router measurement GIL-couples the router to the
    replicas and misreports interpreter contention as routing cost —
    the bench_serving client lesson again). Paired order-alternated
    rounds; overhead from STEADY-STATE MEANS with the first paired
    round dropped — the same reasoning as tracing_overhead_pct above."""
    from predictionio_tpu.cli.pio import resolve_concrete_port

    replica_args = ["--items", str(items), "--rank", str(rank),
                    "--batch-max", str(batch_max)]
    pool = [f"u{i}" for i in range(DEF_POOL)]
    # an EVEN number of client processes so the direct phase splits
    # clients across the two replicas symmetrically
    procs = max(2, procs + (procs % 2))
    direct_rounds: list[float] = []
    router_rounds: list[float] = []
    direct_best = router_best = None
    # every spawn happens INSIDE the try and registers itself as it
    # starts: a failed later spawn must tear down the earlier children
    children: list = []
    router_workers: list = []
    try:
        for _ in range(2):
            children.append(_spawn("replica", replica_args))
        replica_ports = [port for _, port in children]
        # TWO router workers on one SO_REUSEPORT port (`pio router
        # --workers 2`): one CPython router process saturates its GIL
        # at ~200 qps on this host while the 2-replica fleet clears
        # ~300 — the router tier scales horizontally exactly like the
        # model tier
        router_port = resolve_concrete_port("127.0.0.1", 0)
        backend_args = [a for port in replica_ports
                        for a in ("--backend", f"127.0.0.1:{port}")]
        for _ in range(2):
            router_workers.append(
                _spawn("router", [*backend_args, "--port",
                                  str(router_port), "--reuse-port"])[0])
        for i in range(rounds):
            pair = [(replica_ports, "d"), ([router_port], "r")]
            if i % 2:
                pair.reverse()
            for ports, tag in pair:
                r = _drive(ports, pool, clients, per_client,
                           rounds=1, procs=procs)
                if tag == "d":
                    direct_rounds.append(r["qps"])
                    if direct_best is None or r["qps"] > direct_best["qps"]:
                        direct_best = r
                else:
                    router_rounds.append(r["qps"])
                    if router_best is None or r["qps"] > router_best["qps"]:
                        router_best = r
        router_stats: dict = {}
        for worker in router_workers:
            worker.stdin.close()         # worker prints stats and exits
            for field, value in json.loads(
                    worker.stdout.readline()).items():
                router_stats[field] = router_stats.get(field, 0) + value
    finally:
        # exception-safe teardown: one wedged child must not leak the
        # rest (a raised wait() would skip every later kill)
        for proc in [p for p, _ in children] + router_workers:
            try:
                if proc.stdin and not proc.stdin.closed:
                    proc.stdin.close()
                proc.wait(timeout=15)
            except Exception:
                proc.kill()

    return {
        "metric": f"router_overhead_{clients}c",
        "value": round(
            (1.0 - _steady_mean(router_rounds)
             / _steady_mean(direct_rounds)) * 100.0, 2),
        "unit": "pct",
        "host_cores": os.cpu_count(),
        "host_cores_caveat": host_core_ratio_caveat(),
        "router_qps": router_best["qps"],
        "router_p50_ms": router_best["p50_ms"],
        "router_p99_ms": router_best["p99_ms"],
        "direct_qps": direct_best["qps"],
        "direct_p50_ms": direct_best["p50_ms"],
        "router_round_qps": router_rounds,
        "direct_round_qps": direct_rounds,
        "router_replicas": 2,
        "router_workers": 2,
        "errors": router_best["errors"] + direct_best["errors"],
        "router_retries": router_stats.get("retries", 0),
        "router_sheds": router_stats.get("sheds", 0),
        "router_no_backend": router_stats.get("no_backend", 0),
        "router_group_spills": router_stats.get("group_spills", 0),
        "clients": clients,
    }


# ---------------------------------------------------------------------------
# multi-tenant gateway: 1 vs 2 engines behind one router + quota isolation
# ---------------------------------------------------------------------------

def _run_tenant_round(port: int, tenants: list[dict],
                      warmup: int = DEF_WARMUP) -> dict:
    """One synchronized round with SEVERAL tenants hitting one router
    CONCURRENTLY, each tenant its own client subprocess driving its own
    engine path (``--path``). Returns per-tenant stats keyed by tag —
    the layout the quota-isolation pin needs: tenant A being throttled
    while tenant B's latency is measured in the same instant."""
    children: list = []
    tags: list[str] = []
    cid0 = 0
    for t in tenants:
        children.append(_spawn_client(
            port, t["clients"], t["per_client"], warmup, cid0,
            t["pool_size"], path=t["path"],
            backoff=bool(t.get("backoff"))))
        tags.append(t["tag"])
        cid0 += t["clients"]
    raw, dt = _go(children)
    per_tag: dict[str, dict] = {}
    for tag, out in zip(tags, raw):
        flat = np.asarray(out["lat"])
        served = int(flat.size)
        per_tag[tag] = {
            "qps": round(served / dt, 1),
            "p50_ms": (round(float(np.percentile(flat, 50)) * 1e3, 2)
                       if served else None),
            "p99_ms": (round(float(np.percentile(flat, 99)) * 1e3, 2)
                       if served else None),
            "served": served,
            "errors": int(out["errors"]),
            "status": out.get("status") or {},
        }
    per_tag["wall_s"] = round(dt, 3)
    return per_tag


def bench_gateway(items: int = DEF_ITEMS, rank: int = DEF_RANK,
                  clients: int = DEF_CLIENTS, per_client: int = 50,
                  batch_max: int = 32, rounds: int = 4,
                  quota_qps: float = 25.0) -> dict:
    """The multi-tenant gateway's two pins (docs/fleet.md
    "Multi-engine routing"; BENCH_gateway_rNN.json):

    1. **table cost** — the SAME two replica processes driven through
       one router configured one-engine (both replicas in the default
       group, bare ``/queries.json``) vs two-engine (one replica per
       engine, per-tenant ``/engines/<name>/queries.json`` paths,
       clients split evenly). The only difference is the engine-table
       resolution + per-engine quota hop, so the qps delta is the
       gateway's cost — expected ≈0: route resolution is one dict hit.
    2. **quota isolation** — on the two-engine router, tenant ``rec``
       is driven against a qps quota (runtime ``POST /fleet/engines``
       re-quota, no restart) while tenant ``ecom`` runs the identical
       load as in the unthrottled rounds: ``rec`` must throttle with
       429s and ``ecom``'s p99 must stay within session noise of its
       unthrottled baseline — one tenant's burst spends its own
       budget, never the sibling's.

    Paired order-alternated rounds, steady-state means, every server
    its own process (the bench_router discipline)."""
    replica_args = ["--items", str(items), "--rank", str(rank),
                    "--batch-max", str(batch_max)]
    pool = [f"u{i}" for i in range(DEF_POOL)]
    per_tenant_clients = max(2, clients // 2)
    single_rounds: list[float] = []
    multi_rounds: list[float] = []
    unthrottled_b_p99: list[float] = []
    compliant_b_p99: list[float] = []
    abusive_b_p99: list[float] = []
    throttled_429 = 0
    throttled_a_served = 0
    status_totals: dict[str, int] = {}
    children: list = []
    routers: list = []
    try:
        for _ in range(2):
            children.append(_spawn("replica", replica_args))
        r0, r1 = [port for _, port in children]
        single_proc, single_port = _spawn(
            "router", ["--backend", f"127.0.0.1:{r0}",
                       "--backend", f"127.0.0.1:{r1}"])
        routers.append(single_proc)
        multi_proc, multi_port = _spawn(
            "router", ["--engine", f"name=rec,backend=127.0.0.1:{r0}",
                       "--engine", f"name=ecom,backend=127.0.0.1:{r1}",
                       "--default-engine", "rec"])
        routers.append(multi_proc)

        def tenants(rec_per_client: int, ecom_per_client: int,
                    rec_backoff: bool = False) -> list[dict]:
            return [
                {"tag": "rec", "path": "/engines/rec/queries.json",
                 "clients": per_tenant_clients,
                 "per_client": rec_per_client, "pool_size": len(pool),
                 "backoff": rec_backoff},
                {"tag": "ecom", "path": "/engines/ecom/queries.json",
                 "clients": per_tenant_clients,
                 "per_client": ecom_per_client, "pool_size": len(pool)},
            ]

        def fold_status(doc: dict) -> None:
            for code, n in doc.items():
                status_totals[code] = status_totals.get(code, 0) + n

        # phase 1+2 interleaved: single vs multi, order-alternated
        for i in range(rounds):
            pair = [("s", None), ("m", None)]
            if i % 2:
                pair.reverse()
            for tag, _ in pair:
                if tag == "s":
                    # TWO client processes, matching the two-engine
                    # phase's one-proc-per-tenant layout exactly — on a
                    # small host the client process count shifts
                    # closed-loop throughput, and the table-cost delta
                    # must not fold that in
                    r = _drive([single_port], pool, clients, per_client,
                               rounds=1, procs=2)
                    single_rounds.append(r["qps"])
                    fold_status(r.get("status_counts") or {})
                else:
                    per = _run_tenant_round(
                        multi_port, tenants(per_client, per_client))
                    multi_rounds.append(per["rec"]["qps"]
                                        + per["ecom"]["qps"])
                    if per["ecom"]["p99_ms"]:
                        unthrottled_b_p99.append(per["ecom"]["p99_ms"])
                    fold_status(per["rec"]["status"])
                    fold_status(per["ecom"]["status"])

        # phase 3: throttle tenant rec AT RUNTIME, same layout. The
        # quota toggles PER ROUND through the runtime admin endpoint
        # (no restart — the re-quota satellite exercised for real), so
        # every throttled round has an adjacent unthrottled baseline
        # and the p99 ratio never compares across host-drift blocks.
        #
        # Two over-quota tenant profiles, both sized to stay active
        # through the neighbor's whole measured window (a fixed
        # closed-loop count would otherwise burn through its budget in
        # milliseconds of 429s and leave the window unpressured):
        # - COMPLIANT: honors Retry-After — the isolation pin. Its
        #   request rate collapses to ~quota, so on any host the
        #   neighbor's p99 must hold.
        # - ABUSIVE: ignores Retry-After and hammers. The gateway still
        #   keeps its EXCESS off the replicas (served stays ~quota×wall,
        #   zero 5xx) — but on a 1-core host the spin-looping client
        #   processes themselves steal the shared CPU, so the
        #   neighbor-p99 ratio is reported, not pinned (host_cores
        #   recorded; the distortion is the load generator's, not the
        #   gateway's — see docs/fleet.md).
        # A's qps/wall numbers in these rounds are not comparable to
        # the unthrottled rounds; only its 429/served split is.
        import urllib.request

        def set_quota(qps: float) -> None:
            req = urllib.request.Request(
                f"http://127.0.0.1:{multi_port}/fleet/engines",
                data=json.dumps({"action": "quota", "name": "rec",
                                 "quotaQps": qps,
                                 "quotaBurst": qps}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200

        def throttled_block(rec_per_client: int, rec_backoff: bool,
                            sink: list[float]) -> list[float]:
            nonlocal throttled_429, throttled_a_served
            baselines: list[float] = []
            for i in range(max(2, rounds // 2)):
                pair = ["base", "thr"]
                if i % 2:
                    pair.reverse()
                for tag in pair:
                    if tag == "base":
                        set_quota(0.0)          # explicit unlimited
                        per = _run_tenant_round(
                            multi_port, tenants(per_client, per_client))
                        if per["ecom"]["p99_ms"]:
                            baselines.append(per["ecom"]["p99_ms"])
                    else:
                        set_quota(quota_qps)
                        per = _run_tenant_round(
                            multi_port,
                            tenants(rec_per_client, per_client,
                                    rec_backoff=rec_backoff))
                        if per["ecom"]["p99_ms"]:
                            sink.append(per["ecom"]["p99_ms"])
                        throttled_429 += per["rec"]["status"].get(
                            "429", 0)
                        throttled_a_served += per["rec"]["served"]
                    fold_status(per["rec"]["status"])
                    fold_status(per["ecom"]["status"])
            return baselines

        compliant_base = throttled_block(max(4, per_client // 2), True,
                                         compliant_b_p99)
        abusive_base = throttled_block(per_client * 20, False,
                                       abusive_b_p99)

        gateway_stats: dict = {}
        for proc in routers:
            proc.stdin.close()
            doc = json.loads(proc.stdout.readline())
            for engine, counts in (doc.get("per_engine") or {}).items():
                for field, value in counts.items():
                    key = f"{engine}_{field}"
                    gateway_stats[key] = gateway_stats.get(key, 0) + value
    finally:
        for proc in [p for p, _ in children] + routers:
            try:
                if proc.stdin and not proc.stdin.closed:
                    proc.stdin.close()
                proc.wait(timeout=15)
            except Exception:
                proc.kill()

    import os

    def _mean(values: list[float]) -> float | None:
        return sum(values) / len(values) if values else None

    # each block's ratio uses its OWN interleaved baselines — the
    # throttled rounds alternate with unthrottled ones on the same
    # layout, so host drift between blocks never enters the ratio
    b_base = _mean(unthrottled_b_p99)
    b_compliant = _mean(compliant_b_p99)
    b_abusive = _mean(abusive_b_p99)
    base_c = _mean(compliant_base)
    base_a = _mean(abusive_base)
    http_5xx = sum(n for code, n in status_totals.items()
                   if code.startswith("5"))
    return {
        "metric": f"gateway_quota_neighbor_p99_ratio_{clients}c",
        # the isolation pin: the unthrottled tenant's p99 while its
        # neighbor is being 429'd (Retry-After honored), over its own
        # unthrottled baseline from the ADJACENT interleaved rounds
        "value": (round(b_compliant / base_c, 3)
                  if base_c and b_compliant else None),
        "unit": "x",
        "abusive_neighbor_p99_ratio_x": (
            round(b_abusive / base_a, 3)
            if base_a and b_abusive else None),
        "two_engine_overhead_pct": round(
            (1.0 - _steady_mean(multi_rounds)
             / _steady_mean(single_rounds)) * 100.0, 2),
        "single_engine_qps": round(_steady_mean(single_rounds), 1),
        "two_engine_qps": round(_steady_mean(multi_rounds), 1),
        "single_round_qps": single_rounds,
        "two_engine_round_qps": multi_rounds,
        "b_p99_unthrottled_ms": round(b_base, 2) if b_base else None,
        "b_p99_compliant_base_ms": round(base_c, 2) if base_c else None,
        "b_p99_compliant_throttle_ms": (
            round(b_compliant, 2) if b_compliant else None),
        "b_p99_abusive_base_ms": round(base_a, 2) if base_a else None,
        "b_p99_abusive_throttle_ms": (
            round(b_abusive, 2) if b_abusive else None),
        "quota_qps": quota_qps,
        "throttled_429": throttled_429,
        "throttled_tenant_served": throttled_a_served,
        "status_totals": status_totals,
        "http_5xx": http_5xx,
        "rec_quota_throttled_total": gateway_stats.get(
            "rec_quota_throttled", 0),
        "ecom_quota_throttled_total": gateway_stats.get(
            "ecom_quota_throttled", 0),
        "clients": clients,
        "host_cores": os.cpu_count(),
        "host_cores_caveat": host_core_ratio_caveat(),
    }


def bench_gateway_section(shrunk: bool = False) -> dict:
    """The ``gateway`` section for bench.py's round artifact. Shrunk
    (--skip-heavy): fewer clients/rounds, same harness contract."""
    if shrunk:
        r = bench_gateway(clients=8, per_client=12, rounds=2,
                          quota_qps=10.0)
    else:
        r = bench_gateway(per_client=24)
    return {
        "gateway_quota_neighbor_p99_ratio_x": r["value"],
        "gateway_abusive_neighbor_p99_ratio_x":
            r["abusive_neighbor_p99_ratio_x"],
        "gateway_two_engine_overhead_pct": r["two_engine_overhead_pct"],
        "gateway_throttled_429": r["throttled_429"],
        "gateway_http_5xx": r["http_5xx"],
        "gateway_host_cores": r["host_cores"],
        "gateway_host_cores_caveat": r["host_cores_caveat"],
    }


# ---------------------------------------------------------------------------
# ANN retrieval: catalog-size sweep, brute vs IVF-flat + exact rescore
# ---------------------------------------------------------------------------

#: the catalog-size sweep (PR 8): 100k is the classic bench point
#: (brute is still comfortable), 1M is the north-star scale where
#: O(catalog) scoring breaks down. 10M does NOT fit this host: the
#: factor table + index alone pass 3GB and the k-means build runs
#: ~20 min on 2 cores — documented, not attempted.
DEF_ANN_SIZES = (100_000, 1_000_000)
#: taste clusters in the synthetic factor mixture — ALS factor tables
#: are clustered (that structure is what IVF exploits, and what the
#: recall numbers are measured against)
DEF_ANN_CLUSTERS = 256


def _clustered_factors(n: int, rank: int, clusters: int, seed: int,
                       noise: float = 0.5) -> np.ndarray:
    rng = np.random.default_rng(seed)
    centers = (rng.standard_normal((clusters, rank)) * 2.0).astype(np.float32)
    asg = rng.integers(0, clusters, size=n)
    out = centers[asg] + rng.standard_normal((n, rank)).astype(np.float32) * noise
    return np.ascontiguousarray(out, dtype=np.float32)


def _deployed_from_model(model) -> "object":
    from predictionio_tpu.controller.base import FirstServing
    from predictionio_tpu.storage.base import EngineInstance
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.workflow.deploy import DeployedEngine

    algo = rec.ALSAlgorithm(
        rec.ALSAlgorithmParams(rank=model.rank, use_mesh=False))
    now = datetime.datetime.now(datetime.timezone.utc)
    instance = EngineInstance(
        id="bench-ann", status="COMPLETED", start_time=now,
        completion_time=now, engine_id="bench-ann", engine_version="1",
        engine_variant="bench-ann", engine_factory="bench-ann",
    )
    return DeployedEngine(None, instance, [algo], FirstServing(), [model])


def _ann_models(items: int, rank: int, clusters: int, users: int = 2048,
                seed: int = 7):
    """(brute_model, ann_model, item_f, user_f): two ALSModels sharing
    the SAME device factor tables (and later the same index object), so
    the sweep's two servers differ only in retrieval dispatch."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.utils.bimap import BiMap, EntityIdIxMap

    rng = np.random.default_rng(seed)
    item_f = _clustered_factors(items, rank, clusters, seed=seed)
    user_f = _clustered_factors(users, rank, clusters, seed=seed + 1)
    seen = {
        u: rng.choice(items, size=8, replace=False).astype(np.int32)
        for u in range(users)
    }
    uf = jax.device_put(jnp.asarray(user_f))
    itf = jax.device_put(jnp.asarray(item_f))
    uids = EntityIdIxMap(BiMap({f"u{i}": i for i in range(users)}))
    iids = EntityIdIxMap(BiMap({f"i{i}": i for i in range(items)}))
    mk = lambda: ALSModel(rank=rank, user_factors=uf, item_factors=itf,
                          user_ids=uids, item_ids=iids, seen_by_user=seen)
    return mk(), mk(), item_f, user_f


def bench_ann(sizes: tuple = DEF_ANN_SIZES, rank: int = DEF_RANK,
              clients: int = DEF_CLIENTS, per_client: int = DEF_PER_CLIENT,
              batch_max: int = 32, rounds: int = 4,
              procs: int = DEF_CLIENT_PROCS,
              clusters: int = DEF_ANN_CLUSTERS,
              quality_queries: int = 64) -> dict:
    """Catalog-size sweep: brute force vs ANN (IVF-flat MIPS + exact
    rescore, ops/ann) over HTTP at equal client count.

    Both modes run the SAME adaptive micro-batcher config: brute needs
    it (the shared full-table traversal amortizing across the batch is
    its only defense at catalog scale), and ANN — whose lax.map keeps
    batched rows at the B=1 device rate, so batching buys no DEVICE
    win — still profits because a batch amortizes the per-dispatch
    host cost (parse/bind/dispatch/GIL), which on this 2-core host is
    comparable to the probe itself. Quality is measured, not assumed: a
    small nprobe ladder reports recall@shortlist and MAP@10 vs brute
    (the exact ground truth from the same factor tables), and the
    served nprobe is the smallest rung meeting recall >= 0.95 and
    MAP@10 within 1% of brute — the deployment recipe
    docs/serving-performance.md documents."""
    from predictionio_tpu.api.engine_server import EngineServer
    from predictionio_tpu.ops import ann as ann_ops
    from predictionio_tpu.templates import recommendation as rec
    from predictionio_tpu.workflow.deploy import ServerConfig

    per_size = []
    for n_items in sizes:
        brute_model, ann_model, item_f, user_f = _ann_models(
            n_items, rank, clusters)
        t0 = time.perf_counter()
        index = ann_ops.build_index(item_f, seed=0)
        build_s = round(time.perf_counter() - t0, 1)
        assert index is not None, f"catalog {n_items} below index minimum"

        # quality ladder: recall/MAP vs brute at increasing nprobe; the
        # served point is the first rung inside the quality tolerance
        auto = index.clamp_nprobe(0)
        ladder, serving_nprobe = [], None
        for nprobe in sorted({auto, min(auto * 2, index.nlist),
                              min(auto * 4, index.nlist)}):
            q = ann_ops.quality_vs_brute(
                index, user_f[:quality_queries], item_f, k=10,
                nprobe=nprobe)
            rung = {
                "nprobe": nprobe,
                "shortlist_width": q["shortlist_width"],
                "recall_at_shortlist": round(q["recall_at_shortlist"], 4),
                "map_at_10": round(q["map_at_k"], 4),
            }
            ladder.append(rung)
            if (serving_nprobe is None
                    and q["recall_at_shortlist"] >= 0.95
                    and q["map_at_k"] >= 0.99):
                serving_nprobe = nprobe
                served = rung
        if serving_nprobe is None:       # serve the best rung, honestly
            serving_nprobe = ladder[-1]["nprobe"]
            served = ladder[-1]

        ann_model.ann_index = index
        ann_model.configure_retrieval("ann", nprobe=serving_nprobe)
        brute_deployed = _deployed_from_model(brute_model)
        ann_deployed = _deployed_from_model(ann_model)
        warm_batch_signatures(brute_deployed, batch_max)
        warm_batch_signatures(ann_deployed, batch_max)
        ann_deployed.query(rec.Query(user="u0", num=10))  # compile B=1

        # device-dispatch phase: the retrieval op itself, measured
        # single-threaded in-process (interleaved, best of N). The HTTP
        # phase below measures the SYSTEM — on this 2-core GIL-bound
        # host its ~2.5ms/query serving floor (ROADMAP item 2)
        # compresses any device-side ratio toward the floor, and the
        # in-host load generator taxes the faster server
        # disproportionately (more responses/sec to drive). Reporting
        # both keeps the artifact honest about which layer owns the gap.
        device = {"brute_b1_ms": None, "ann_b1_ms": None,
                  "brute_batch_ms_per_q": None, "ann_batch_ms_per_q": None}
        buixs = np.arange(batch_max, dtype=np.int32)
        bcols = np.zeros((batch_max, 512), dtype=np.int32)
        bmask = np.zeros((batch_max, 512), dtype=np.float32)
        for _ in range(3):
            for model, tag in ((brute_model, "brute"), (ann_model, "ann")):
                t0 = time.perf_counter()
                for i in range(20):
                    model.recommend(f"u{i}", 10)
                b1 = (time.perf_counter() - t0) / 20 * 1000
                vals, _ = model.batch_topk(buixs, bcols, bmask, None, 10)
                np.asarray(vals)                      # block until done
                t0 = time.perf_counter()
                for _ in range(5):
                    vals, _ = model.batch_topk(buixs, bcols, bmask,
                                               None, 10)
                    np.asarray(vals)
                bq = ((time.perf_counter() - t0) / 5 / batch_max) * 1000
                key = f"{tag}_b1_ms"
                if device[key] is None or b1 < device[key]:
                    device[key] = b1
                key = f"{tag}_batch_ms_per_q"
                if device[key] is None or bq < device[key]:
                    device[key] = bq
        device = {k: round(v, 2) for k, v in device.items()}
        device["device_speedup_b1_x"] = round(
            device["brute_b1_ms"] / device["ann_b1_ms"], 2)
        device["device_speedup_batch_x"] = round(
            device["brute_batch_ms_per_q"] / device["ann_batch_ms_per_q"],
            2)

        serving_cfg = dict(ip="127.0.0.1", port=0, batching=True,
                           batch_policy="adaptive", batch_max=batch_max,
                           batch_wait_ms=5.0)
        brute_server = EngineServer(brute_deployed,
                                    ServerConfig(**serving_cfg))
        ann_server = EngineServer(ann_deployed, ServerConfig(**serving_cfg))
        brute_server.start()
        ann_server.start()
        pool = [f"u{i}" for i in range(DEF_POOL)]
        brute = ann = None
        try:
            for i in range(rounds):
                # order-alternated rounds: the headline is a ratio, and
                # a fixed phase order folds host drift into it
                pair = [("brute", brute_server), ("ann", ann_server)]
                if i % 2:
                    pair.reverse()
                for tag, server in pair:
                    r = _drive(server.port, pool, clients, per_client,
                               rounds=1, procs=procs)
                    if tag == "brute":
                        if brute is None or r["qps"] > brute["qps"]:
                            brute = r
                    else:
                        if ann is None or r["qps"] > ann["qps"]:
                            ann = r
            astats = _stats_doc(ann_server.port)
        finally:
            brute_server.stop()
            ann_server.stop()

        assert astats["annEnabled"], "ann server must serve via the index"
        per_size.append({
            "items": n_items,
            "nlist": index.nlist,
            "max_cell": index.max_cell,
            "build_s": build_s,
            "served_nprobe": serving_nprobe,
            "shortlist_width": served["shortlist_width"],
            "recall_at_shortlist": served["recall_at_shortlist"],
            "map_at_10": served["map_at_10"],
            "map_delta_vs_brute": round(1.0 - served["map_at_10"], 4),
            "quality_ladder": ladder,
            "brute_qps": brute["qps"],
            "brute_p50_ms": brute["p50_ms"],
            "brute_p99_ms": brute["p99_ms"],
            "ann_qps": ann["qps"],
            "ann_p50_ms": ann["p50_ms"],
            "ann_p99_ms": ann["p99_ms"],
            "speedup_x": round(ann["qps"] / brute["qps"], 2)
            if brute["qps"] else None,
            "p99_ratio_x": round(brute["p99_ms"] / ann["p99_ms"], 2)
            if ann["p99_ms"] else None,
            "errors": brute["errors"] + ann["errors"],
            "ann_queries_counted": astats["serving"]["annQueries"],
            "device": device,
        })

    largest = per_size[-1]
    return {
        "metric": f"ann_vs_brute_speedup_{largest['items'] // 1000}k_x",
        "value": largest["speedup_x"],
        "unit": "x",
        "clients": clients,
        "rank": rank,
        "brute_config": f"adaptive batching (batch_max={batch_max})",
        "ann_config": f"adaptive batching (batch_max={batch_max})",
        "sizes": per_size,
    }


def bench_ann_section(shrunk: bool = False) -> dict:
    """The ``ann_retrieval`` section for bench.py's round artifact.
    ``shrunk`` (--skip-heavy) runs one indexable-but-small catalog so
    the harness contract stays exercised without the 1M build."""
    if shrunk:
        r = bench_ann(sizes=(16_384,), per_client=8, rounds=1)
    else:
        r = bench_ann(per_client=16)
    out = {}
    for s in r["sizes"]:
        suffix = f"{s['items'] // 1000}k"
        out[f"ann_speedup_{suffix}_x"] = s["speedup_x"]
        out[f"ann_p99_ratio_{suffix}_x"] = s["p99_ratio_x"]
        out[f"ann_device_speedup_{suffix}_x"] = \
            s["device"]["device_speedup_batch_x"]
        out[f"ann_qps_{suffix}"] = s["ann_qps"]
        out[f"ann_brute_qps_{suffix}"] = s["brute_qps"]
        out[f"ann_recall_{suffix}"] = s["recall_at_shortlist"]
        out[f"ann_map10_{suffix}"] = s["map_at_10"]
    return out


def bench_section(clients: int = DEF_CLIENTS) -> dict:
    """The ``serving_path`` section for bench.py's round artifact:
    the same phases at reduced volume, keys prefixed for the merged
    BENCH line."""
    r = bench_serving(clients=clients, per_client=16)
    rt = bench_router(clients=clients, per_client=16)
    return {
        f"serving_qps_adaptive_{clients}c": r["value"],
        f"serving_qps_per_query_{clients}c": r["per_query_qps"],
        "serving_speedup_x": r["speedup_vs_per_query_x"],
        "serving_p95_ms": r["p95_ms"],
        "serving_traced_qps": r["traced_qps"],
        "serving_tracing_overhead_pct": r["tracing_overhead_pct"],
        "serving_cached_qps": r["cached_qps"],
        "serving_cache_hit_ratio": r["cache_hit_ratio"],
        "serving_router_qps": rt["router_qps"],
        "serving_router_overhead_pct": rt["value"],
        "serving_router_host_cores": rt["host_cores"],
        "serving_router_host_cores_caveat": rt["host_cores_caveat"],
    }


def main() -> None:
    import sys

    if "--client" in sys.argv:
        # load-generator subprocess entry (spawned by _run_round)
        _client_main([a for a in sys.argv[1:] if a != "--client"])
        return
    if "--replica" in sys.argv:
        # replica-server subprocess entry (spawned by bench_router)
        _replica_main([a for a in sys.argv[1:] if a != "--replica"])
        return
    if "--router" in sys.argv:
        _router_main([a for a in sys.argv[1:] if a != "--router"])
        return
    if "--serving-worker" in sys.argv:
        # prefork-pool sibling entry (spawned by bench_workers)
        _serving_worker_main(
            [a for a in sys.argv[1:] if a != "--serving-worker"])
        return
    # 48+ threads at CPython's default 5ms GIL switch interval add
    # multi-ms scheduling jitter per request; tighten it for the
    # serving process (the client processes do the same)
    sys.setswitchinterval(0.0005)
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--items", type=int, default=DEF_ITEMS)
    parser.add_argument("--rank", type=int, default=DEF_RANK)
    parser.add_argument("--clients", type=int, default=DEF_CLIENTS)
    parser.add_argument("--per-client", type=int, default=DEF_PER_CLIENT)
    parser.add_argument("--batch-max", type=int, default=32)
    parser.add_argument("--client-procs", type=int, default=DEF_CLIENT_PROCS)
    parser.add_argument("--router-only", action="store_true",
                        help="run only the fleet-router overhead phase")
    parser.add_argument("--gateway-only", action="store_true",
                        help="run only the multi-tenant gateway phase "
                             "(1 vs 2 engines + quota isolation; "
                             "BENCH_gateway_rNN.json)")
    parser.add_argument("--gateway-rounds", type=int, default=4)
    parser.add_argument("--gateway-quota-qps", type=float, default=25.0)
    parser.add_argument("--ann-only", action="store_true",
                        help="run only the ANN catalog-size sweep")
    parser.add_argument("--ann-sizes", type=int, nargs="+", default=None,
                        help="catalog sizes for the ANN sweep")
    parser.add_argument("--workers-only", action="store_true",
                        help="run only the prefork-pool core-scaling "
                             "phase (BENCH_workers_rNN.json)")
    parser.add_argument("--workers-ann-items", type=int, default=1_000_000,
                        help="catalog size for the ANN re-run under 2 "
                             "workers (0 skips it)")
    parser.add_argument("--workers-rounds", type=int, default=6)
    parser.add_argument("--shm-only", action="store_true",
                        help="run only the shared-memory serving-plane "
                             "phase (private LRU vs shm segment at 1 "
                             "and 2 workers; BENCH_shm_rNN.json)")
    parser.add_argument("--shm-rounds", type=int, default=4)
    parser.add_argument("--shm-rewarm-keys", type=int, default=16)
    args = parser.parse_args()
    if args.shm_only:
        print(json.dumps(bench_shm(
            items=args.items, rank=args.rank, clients=args.clients,
            per_client=args.per_client, batch_max=args.batch_max,
            rounds=args.shm_rounds, procs=args.client_procs,
            rewarm_keys=args.shm_rewarm_keys)))
        return
    if args.gateway_only:
        # --client-procs deliberately NOT forwarded: both arms of the
        # table-cost comparison pin the client layout at one process
        # per tenant (two total) so the paired ratio never folds a
        # client-topology difference in
        print(json.dumps(bench_gateway(
            items=args.items, rank=args.rank, clients=args.clients,
            per_client=args.per_client, batch_max=args.batch_max,
            rounds=args.gateway_rounds,
            quota_qps=args.gateway_quota_qps)))
        return
    if args.workers_only:
        print(json.dumps(bench_workers(
            items=args.items, rank=args.rank, clients=args.clients,
            per_client=args.per_client, batch_max=args.batch_max,
            rounds=args.workers_rounds, procs=args.client_procs,
            ann_items=args.workers_ann_items or None)))
        return
    if args.ann_only:
        print(json.dumps(bench_ann(
            sizes=tuple(args.ann_sizes or DEF_ANN_SIZES), rank=args.rank,
            clients=args.clients, per_client=args.per_client,
            batch_max=args.batch_max, procs=args.client_procs)))
        return
    if not args.router_only:
        print(json.dumps(bench_serving(
            items=args.items, rank=args.rank, clients=args.clients,
            per_client=args.per_client, batch_max=args.batch_max,
            procs=args.client_procs)))
    print(json.dumps(bench_router(
        items=args.items, rank=args.rank, clients=args.clients,
        per_client=args.per_client, batch_max=args.batch_max,
        procs=args.client_procs)))
    print(json.dumps(bench_ann(
        sizes=tuple(args.ann_sizes or DEF_ANN_SIZES), rank=args.rank,
        clients=args.clients, per_client=args.per_client,
        batch_max=args.batch_max, procs=args.client_procs)))


if __name__ == "__main__":
    main()
